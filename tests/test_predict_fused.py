"""r18 fused predict mega-kernel: quantized-space parity + residency.

Covers the r18 acceptance surface at both levels:

* kernel level — ``predict_forest_pallas`` over a ``pack_forest_soa``
  layout matches the legacy ``predict_forest_binned`` scan bit-exactly
  across precision {f32, bf16, int8} x tree shape {balanced, ragged,
  single-leaf}, including staged ``num_iteration``/``start_iteration``
  windows and grower garbage sentinels left in dead node slots;
* runtime level — the fused device path matches the lazily-built numpy
  oracle for trained (ragged) and multiclass forests, bin-edge rows
  route identically in quantized and f32 space (``code <= threshold``
  is the SAME integer comparison), ``ThresholdBoundError`` still rejects
  out-of-range thresholds at ingest, categorical forests fall back to
  the legacy path, the stats counters account mega-kernel launches, the
  resident SoA keeps the compact storage dtypes (no f32/i32 node table
  for int8/bf16 — the byte contract of ``PACKED_NODE_BYTES``), and
  ``warm()`` covers the full (bucket, raw_score, route) compile key so
  a post-warm quantized dp traffic sweep compiles nothing.

dp bit-identity and tp ulp parity for the fused path ride the existing
matrix in test_serving_mesh.py (the runtimes there serve on the fused
path now); this file pins what is NEW in r18.
"""

import numpy as np
import pytest

import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import BinMapper
from lightgbm_tpu.models.tree import Tree
from lightgbm_tpu.ops import quantize as qz
from lightgbm_tpu.ops.predict import (
    PREDICT_TREE_CHUNKS,
    forest_depth_cap,
    pack_forest_soa,
    predict_forest_binned,
    predict_forest_pallas,
    soa_tree_chunk,
)
from lightgbm_tpu.serving import (
    PackedForest,
    PredictorRuntime,
    ThresholdBoundError,
    pack_booster,
)

TOL = 1e-6


# ---------------------------------------------------------------------------
# kernel-level parity matrix (no runtime, interpret-mode Pallas)
# ---------------------------------------------------------------------------
def _rand_tree(rng, m, f, num_bins, shape):
    """One tree's arrays with grower-style garbage in dead slots."""
    feat = np.zeros(m, np.int32)
    thr = np.zeros(m, np.int32)
    left = -np.ones(m, np.int32)
    right = -np.ones(m, np.int32)
    leafv = np.zeros(m, np.float32)
    isl = np.zeros(m, bool)
    if shape == "single-leaf":
        isl[0] = True
        leafv[0] = rng.normal()
        leafv[1:] = 999.0                 # dead-slot sentinels must not leak
        return feat, thr, left, right, leafv, isl
    n_nodes, frontier = 1, [0]
    while frontier and n_nodes + 2 <= m:
        i = frontier.pop(rng.integers(len(frontier)))
        if shape == "ragged" and rng.random() < 0.3 and i != 0:
            isl[i] = True
            leafv[i] = rng.normal()
            continue
        feat[i] = rng.integers(f)
        thr[i] = rng.integers(0, num_bins)
        left[i], right[i] = n_nodes, n_nodes + 1
        frontier += [n_nodes, n_nodes + 1]
        n_nodes += 2
    for i in frontier:
        isl[i] = True
        leafv[i] = rng.normal()
    leafv[~isl & (left < 0)] = 777.0      # garbage in dead slots
    return feat, thr, left, right, leafv, isl


def _rand_forest(seed, t=5, m=11, f=4, num_bins=8, shape="ragged"):
    rng = np.random.default_rng(seed)
    shapes = [shape] * t
    if shape == "ragged":                 # mix in one degenerate tree
        shapes[t // 2] = "single-leaf"
    arrs = [_rand_tree(rng, m, f, num_bins, s) for s in shapes]
    feat, thr, left, right, leafv, isl = (np.stack(x) for x in zip(*arrs))
    forest = Tree(
        split_feature=jnp.asarray(feat), split_bin=jnp.asarray(thr),
        left=jnp.asarray(left), right=jnp.asarray(right),
        leaf_value=jnp.asarray(leafv), is_leaf=jnp.asarray(isl),
        count=jnp.zeros((t, 1), jnp.int8),
        split_gain=jnp.zeros((t, 1), jnp.int8),
        num_leaves=jnp.zeros(t, jnp.int32))
    bins = rng.integers(0, num_bins, (37, f)).astype(np.uint8)
    return (feat, thr, left, right, leafv, isl), forest, bins


@pytest.mark.parametrize("shape", ["balanced", "ragged", "single-leaf"])
@pytest.mark.parametrize("precision", ["f32", "bf16", "int8"])
def test_kernel_parity_matrix(precision, shape):
    (feat, thr, left, right, leafv, isl), forest, bins = _rand_forest(
        seed=hash((precision, shape)) % 2**31, shape=shape)
    t = feat.shape[0]
    cap = forest_depth_cap(forest)
    if precision == "f32":
        soa = pack_forest_soa(feat, thr, left, right, leafv, isl,
                              precision="f32")
        ref_leaf = leafv
    elif precision == "bf16":
        stored = np.asarray(jnp.asarray(leafv, jnp.bfloat16), np.float32)
        soa = pack_forest_soa(feat, thr, left, right, stored, isl,
                              precision="bf16")
        ref_leaf = stored
    else:
        scale = np.full(t, 0.01, np.float32)
        codes = np.clip(np.round(leafv / scale[:, None]),
                        -127, 127).astype(np.int8)
        soa = pack_forest_soa(feat.astype(np.int16), thr.astype(np.uint8),
                              left.astype(np.int16),
                              right.astype(np.int16), codes, isl,
                              precision="int8", leaf_scale=scale)
        ref_leaf = codes.astype(np.float32) * scale[:, None]
    assert soa_tree_chunk(soa) == PREDICT_TREE_CHUNKS[precision]
    # legacy scan over the SAME stored values = the semantics oracle
    ref_forest = forest._replace(leaf_value=jnp.asarray(ref_leaf))
    ref = predict_forest_binned(ref_forest, jnp.asarray(bins), 0.1, 0.5,
                                jnp.int32(t), cap)
    got = predict_forest_pallas(soa, jnp.asarray(bins), 0.1, 0.5,
                                jnp.int32(t), cap)
    np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                               atol=TOL, rtol=0)
    # staged windows: num/start are traced operands of the round mask
    for k, s in [(2, 0), (3, 1), (1, t - 1)]:
        r = predict_forest_binned(ref_forest, jnp.asarray(bins), 0.1, 0.0,
                                  jnp.int32(k), cap,
                                  start_iteration=jnp.int32(s))
        g = predict_forest_pallas(soa, jnp.asarray(bins), 0.1, 0.0,
                                  jnp.int32(k), cap,
                                  start_iteration=jnp.int32(s))
        np.testing.assert_allclose(np.asarray(g), np.asarray(r),
                                   atol=TOL, rtol=0, err_msg=f"{k=} {s=}")


def test_multiclass_kernel_parity():
    # 3 classes = 3 independent SoAs; the runtime stacks the columns
    per_class = [_rand_forest(seed=100 + c) for c in range(3)]
    bins = per_class[0][2]
    for c, (arrs, forest, _) in enumerate(per_class):
        feat, thr, left, right, leafv, isl = arrs
        soa = pack_forest_soa(feat, thr, left, right, leafv, isl)
        cap = forest_depth_cap(forest)
        ref = predict_forest_binned(forest, jnp.asarray(bins), 0.2, 0.0,
                                    jnp.int32(feat.shape[0]), cap)
        got = predict_forest_pallas(soa, jnp.asarray(bins), 0.2, 0.0,
                                    jnp.int32(feat.shape[0]), cap)
        np.testing.assert_allclose(np.asarray(got), np.asarray(ref),
                                   atol=TOL, rtol=0, err_msg=f"class {c}")


# ---------------------------------------------------------------------------
# runtime-level fixtures
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def reg_packed(small_regression):
    X, y = small_regression
    b = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=10)
    return X, pack_booster(b)


@pytest.fixture(scope="module")
def mc_packed():
    rng = np.random.default_rng(7)
    X = rng.normal(size=(600, 4))
    y = ((X[:, 0] + X[:, 1] > 0).astype(int)
         + (X[:, 2] > 0.5).astype(int)).astype(np.float64)
    b = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=3)
    return X, pack_booster(b)


def _edge_forest(num_bins=8, edge_bin=3):
    """One tree: root splits feature 0 at ``edge_bin``; left leaf -1,
    right leaf +1 — the bin-edge routing probe."""
    t, m = 1, 3
    split_feature = np.zeros((t, m), np.int32)
    split_bin = np.full((t, m), 0, np.int32)
    split_bin[0, 0] = edge_bin
    left = np.full((t, m), -1, np.int32)
    right = np.full((t, m), -1, np.int32)
    left[0, 0], right[0, 0] = 1, 2
    is_leaf = np.zeros((t, m), bool)
    is_leaf[:, 1:] = True
    leaf_value = np.zeros((t, m), np.float32)
    leaf_value[0, 1], leaf_value[0, 2] = -1.0, 1.0
    mapper = BinMapper(
        upper_bounds=[np.arange(num_bins - 1) + 0.5],
        nan_bin=np.full(1, -1, np.int32),
        n_bins=np.full(1, num_bins, np.int32))
    return PackedForest(
        split_feature=split_feature, split_bin=split_bin,
        left=left, right=right, leaf_value=leaf_value, is_leaf=is_leaf,
        is_cat_split=None, cat_mask=None, shrink=1.0,
        init_score=np.zeros(1, np.float32), num_class=1,
        best_iteration=t, depth_cap=1,
        params={"objective": "regression"},
        bin_mapper_dict=mapper.to_dict()).validate()


# ---------------------------------------------------------------------------
# runtime parity + routing + rejection
# ---------------------------------------------------------------------------
@pytest.mark.parametrize("precision", ["f32", "bf16", "int8"])
def test_runtime_oracle_parity(reg_packed, precision):
    X, pf = reg_packed
    rt = PredictorRuntime(pf, max_bucket=256, donate=False,
                          forest_precision=precision)
    assert rt.fused_predict and rt.cache_info()["fused_path"]
    codes = pf.bin_mapper.transform(np.asarray(X[:200], np.float64))
    dev = rt.predict_binned(codes, raw_score=True)
    oracle = rt.oracle.predict_numpy(codes, raw_score=True)
    assert np.max(np.abs(dev - oracle)) <= 1e-5, precision


def test_runtime_multiclass_parity(mc_packed):
    X, pf = mc_packed
    rt = PredictorRuntime(pf, max_bucket=128, donate=False,
                          forest_precision="int8")
    assert rt.kernel_launches_per_dispatch == 3      # one kernel per class
    codes = pf.bin_mapper.transform(np.asarray(X[:100], np.float64))
    dev = rt.predict_binned(codes, raw_score=True)
    oracle = rt.oracle.predict_numpy(codes, raw_score=True)
    assert dev.shape == (100, 3)
    assert np.max(np.abs(dev - oracle)) <= 1e-5


@pytest.mark.parametrize("precision", ["f32", "bf16", "int8"])
def test_bin_edge_routes_left(precision):
    # code <= threshold goes LEFT; the quantized path compares the SAME
    # stored u8 bin codes, so the edge row lands identically
    pf = _edge_forest(edge_bin=3)
    rt = PredictorRuntime(pf, max_bucket=16, donate=False,
                          forest_precision=precision)
    codes = np.arange(8, dtype=np.uint8)[:, None]
    out = rt.predict_binned(codes, raw_score=True)
    want = np.where(np.arange(8) <= 3, -1.0, 1.0)
    np.testing.assert_allclose(out, want, atol=1e-5)
    oracle = rt.oracle.predict_numpy(codes, raw_score=True)
    np.testing.assert_allclose(out, oracle, atol=1e-5)


def test_threshold_bound_rejected_at_ingest(reg_packed):
    _, pf = reg_packed
    bad_bin = pf.split_bin.copy()
    bad_bin[0, int(np.argmin(pf.is_leaf[0]))] = 300
    import dataclasses

    bad = dataclasses.replace(pf, split_bin=bad_bin)
    with pytest.raises(ThresholdBoundError, match="split_bin"):
        PredictorRuntime(bad, max_bucket=16, donate=False,
                         forest_precision="int8")


# ---------------------------------------------------------------------------
# residency: compact dtypes stay resident, no f32/i32 node table
# ---------------------------------------------------------------------------
def test_soa_residency_byte_contract(reg_packed):
    X, pf = reg_packed
    for precision, idx_t, thr_t, leaf_t in (
            ("int8", np.int16, np.uint8, jnp.int8),
            ("bf16", np.int16, np.uint8, jnp.bfloat16)):
        rt = PredictorRuntime(pf, max_bucket=64, donate=False,
                              forest_precision=precision)
        (soa,) = rt._soa
        assert soa.split_feature.dtype == idx_t
        assert soa.left.dtype == idx_t and soa.right.dtype == idx_t
        assert soa.split_bin.dtype == thr_t
        assert soa.leaf.dtype == leaf_t
        # no node field is 4 bytes wide -> zero f32 (or i32) table bytes
        assert max(a.dtype.itemsize
                   for a in (soa.split_feature, soa.split_bin, soa.left,
                             soa.right, soa.leaf)) <= 2
        # per-slot bytes match the r14 layout contract the SLO budgets
        # and the analysis model both charge
        per_slot = sum(a.dtype.itemsize
                       for a in (soa.split_feature, soa.split_bin,
                                 soa.left, soa.right, soa.leaf,
                                 soa.is_leaf))
        assert per_slot == qz.PACKED_NODE_BYTES[precision]


def test_analysis_model_matches_layout_contract():
    from lightgbm_tpu.analysis.budgets import (PREDICT_SOA_NODE_BYTES,
                                               predict_kernel_time)

    assert PREDICT_SOA_NODE_BYTES == qz.PACKED_NODE_BYTES
    m = predict_kernel_time(precision="int8")
    assert m["f32_node_table_bytes"] == 0
    assert m["launch_drop_x"] >= 4.0
    assert m["vmem_block_mb"] <= 16.0
    assert predict_kernel_time(precision="bf16")["f32_node_table_bytes"] \
        == 0


def test_cat_forest_falls_back_to_legacy(small_regression):
    X, y = small_regression
    rng = np.random.default_rng(3)
    Xc = np.column_stack([rng.integers(0, 8, len(y)).astype(float),
                          X[:, :2]])
    b = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1,
         "min_data_in_leaf": 5},
        lgb.Dataset(Xc, label=y, categorical_feature=[0]),
        num_boost_round=4)
    rt = PredictorRuntime(pack_booster(b), max_bucket=32, donate=False)
    assert not rt.fused_predict
    assert rt.kernel_launches_per_dispatch == 0
    rt.predict(Xc[:10])
    snap = rt.stats.snapshot()
    assert snap["predict_kernel_launches"] == 0
    assert snap["fused_path"]["dispatches"] == 0
    assert snap["fused_path"]["legacy_dispatches"] >= 1


# ---------------------------------------------------------------------------
# stats accounting + full-compile-key warm (the r18 zero-recompile pin)
# ---------------------------------------------------------------------------
def test_stats_count_kernel_launches(mc_packed):
    X, pf = mc_packed
    rt = PredictorRuntime(pf, max_bucket=64, donate=False,
                          forest_precision="int8")
    for n in (5, 40, 64):
        rt.predict(X[:n])
    snap = rt.stats.snapshot()
    assert snap["fused_path"]["dispatches"] == 3
    assert snap["fused_path"]["legacy_dispatches"] == 0
    # 3 dispatches x num_class mega-kernels each
    assert snap["predict_kernel_launches"] == 3 * 3
    assert rt.cache_info()["kernel_launches_per_dispatch"] == 3


def test_warm_covers_full_compile_key_quantized_dp(reg_packed):
    X, pf = reg_packed
    # cache must hold the full warmed key set: 8-bucket ladder x 2
    # raw_score settings (the LRU would otherwise evict early warms —
    # documented warm() semantics)
    rt = PredictorRuntime(pf, max_bucket=128, donate=False,
                          forest_precision="int8", mesh_devices=4,
                          shard_policy="dp", max_cache_entries=32)
    for raw in (False, True):
        rt.warm(raw_score=raw)
    keys = set(rt.warmed_keys)
    # every bucket warmed at both raw_score settings, on its traffic route
    assert {k[0] for k in keys} == set(rt.buckets)
    assert {k[1] for k in keys} == {False, True}
    assert all(k[2] == rt.route_for(k[0]) for k in keys)
    assert "dp" in {k[2] for k in keys}               # shard program warmed
    before = rt.num_compiles
    for n in (1, 3, 17, 64, 100, 128):
        for raw in (False, True):
            rt.predict(X[:n], raw_score=raw)
    assert rt.num_compiles == before                  # zero traffic compiles

"""Freshness pipeline tests (ISSUE r15 tentpole + satellites).

The continuous refresh loop: streamed model-file continuation (the
lifted fence) with schema-digest enforcement, ``Dataset.from_blocks``
schema pinning via ``reference=``, the RefreshDaemon's
data-arrival -> continue-train -> publish -> canary -> flip loop on a
deterministic sim clock with chaos at every new fault site, the
staleness tracker/SLO arithmetic, the ``task=refresh`` CLI contract,
and the analytic FRESHNESS_BUDGETS.
"""

import io
import json
import os

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.__main__ import _refresh, main as cli_main
from lightgbm_tpu.analysis.budgets import (FRESHNESS_BUDGETS,
                                           check_freshness_budgets,
                                           freshness_budget_by_name,
                                           staleness_model)
from lightgbm_tpu.data.sketch import schema_digest
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.faults import (PIPELINE_SITES, SERVING_SITES, SITES,
                                 SWEEP_SITES, TRAINING_SITES,
                                 FaultInjector, FaultSpec)
from lightgbm_tpu.models.gbdt import Booster
from lightgbm_tpu.pipeline import (ArrivalFeed, DirectoryFeed, RefreshDaemon,
                                   RefreshRecord, SimClock, StalenessTracker,
                                   latest_artifact)
from lightgbm_tpu.serving.packed import PackedForest, pack_booster
from lightgbm_tpu.training import latest_checkpoint, train_resumable

PARAMS = dict(objective="binary", num_leaves=7, learning_rate=0.2,
              max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7,
              stream_block_rows=256)
# dyadic stage costs -> exact float sums -> exact staleness assertions
COSTS = dict(dataset_build=0.5, train_round=0.25, publish=0.25,
             deploy=1.0, flip=0.5)


def _problem(n=512, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
    return X, y


def _blocks(X, y, rows=256):
    return [(X[lo:lo + rows], y[lo:lo + rows])
            for lo in range(0, len(X), rows)]


def _trees_equal(a, b):
    ta = a.trees if hasattr(a, "trees") else a
    tb = b.trees if hasattr(b, "trees") else b
    if len(ta) != len(tb):
        return False
    return all(np.array_equal(np.asarray(getattr(x, f)),
                              np.asarray(getattr(y, f)))
               for x, y in zip(ta, tb)
               for f in ("split_feature", "split_bin", "left", "right",
                         "leaf_value", "is_leaf"))


def _daemon(state_dir, clock, *, injector=None, stage_costs=None,
            refresh_rounds=3, initial_rounds=4, slo_ms=None):
    feed = ArrivalFeed(clock)
    d = RefreshDaemon(PARAMS, str(state_dir), feed=feed,
                      refresh_rounds=refresh_rounds,
                      initial_rounds=initial_rounds,
                      checkpoint_rounds=2, staleness_slo_ms=slo_ms,
                      canary_rows=4, clock=clock, injector=injector,
                      stage_costs=stage_costs)
    return d, feed


# -- satellite 1: streamed model-file continuation (the lifted fence) ----


def test_streamed_continuation_bit_identical_both_codecs(tmp_path):
    X, y = _problem()
    blocks = _blocks(X, y)

    def ds():
        return Dataset.from_blocks(blocks, params=dict(PARAMS))

    ref = lgb.train(dict(PARAMS), ds(), num_boost_round=5)
    base = lgb.train(dict(PARAMS), ds(), num_boost_round=3)
    for codec, name in (("txt", "m.txt"), ("npz", "m.npz")):
        path = str(tmp_path / name)
        if codec == "npz":
            pack_booster(base).save(path)
        else:
            base.save_model(path)
        cont = Booster(model_file=path)
        dsc = ds()
        cont.update(train_set=dsc)
        cont.update()
        assert cont.num_trees() == 5, codec
        assert _trees_equal(ref, cont), codec


def test_streamed_continuation_refuses_rebinned_blocks(tmp_path):
    X, y = _problem()
    base = lgb.train(dict(PARAMS),
                     Dataset.from_blocks(_blocks(X, y),
                                         params=dict(PARAMS)),
                     num_boost_round=2)
    path = str(tmp_path / "m.txt")
    base.save_model(path)
    cont = Booster(model_file=path)
    X2, y2 = _problem(seed=99)
    rebinned = Dataset.from_blocks(_blocks(X2 * 3.0 + 1.0, y2),
                                   params=dict(PARAMS))
    with pytest.raises(ValueError, match="binning|schema"):
        cont.update(train_set=rebinned)


def test_from_blocks_reference_pins_schema_digest():
    X, y = _problem()
    ds1 = Dataset.from_blocks(_blocks(X, y), params=dict(PARAMS))
    ds1.construct()
    X2, y2 = _problem(seed=3)
    grown = _blocks(X, y) + _blocks(X2 * 5.0 - 2.0, y2)
    ds2 = Dataset.from_blocks(grown, params=dict(PARAMS), reference=ds1)
    ds2.construct()
    assert schema_digest(ds2.bin_mapper) == schema_digest(ds1.bin_mapper)
    # without the reference the grown rows shift the quantile sketch
    ds3 = Dataset.from_blocks(grown, params=dict(PARAMS))
    ds3.construct()
    assert schema_digest(ds3.bin_mapper) != schema_digest(ds1.bin_mapper)


def test_from_blocks_reference_rejections():
    X, y = _problem()
    with pytest.raises(ValueError, match="BinMapper"):
        Dataset.from_blocks(_blocks(X, y), params=dict(PARAMS),
                            reference=Dataset(X, label=y))  # unconstructed
    ds1 = Dataset.from_blocks(_blocks(X, y), params=dict(PARAMS))
    ds1.construct()
    with pytest.raises(ValueError, match="reference"):
        Dataset.from_blocks(_blocks(X[:, :3], y), params=dict(PARAMS),
                            reference=ds1).construct()
    # EFB-bundled references can't pin a streamed schema
    rng = np.random.default_rng(5)
    cat = rng.integers(0, 8, 600)
    onehot = np.zeros((600, 8), np.float32)
    onehot[np.arange(600), cat] = 1.0
    Xb = np.concatenate([rng.normal(size=(600, 2)).astype(np.float32),
                         onehot], axis=1)
    yb = (cat % 2).astype(np.float32)
    dsb = lgb.Dataset(Xb, label=yb)
    dsb.construct()
    assert dsb.bin_mapper.bundler is not None
    with pytest.raises(ValueError, match="EFB"):
        Dataset.from_blocks(_blocks(Xb, y[:600]), params=dict(PARAMS),
                            reference=dsb)


# -- satellite 2: shared fault registry grows pipeline sites -------------


def test_pipeline_sites_and_shim_surface():
    assert PIPELINE_SITES == ("data_arrival", "continue_train",
                              "artifact_push", "flip")
    assert SITES == (SERVING_SITES + TRAINING_SITES + PIPELINE_SITES
                     + SWEEP_SITES)
    inj = FaultInjector()
    assert set(PIPELINE_SITES) <= set(inj.hits)
    # the serving shim keeps its pre-move surface, same objects
    from lightgbm_tpu.serving import faults as shim
    import lightgbm_tpu.faults as canonical
    for name in shim.__all__:
        assert getattr(shim, name) is getattr(canonical, name)


# -- train_resumable init_model (the daemon's continuation seed) ---------


def test_train_resumable_init_model_seeds_continuation(tmp_path):
    X, y = _problem()
    blocks = _blocks(X, y)

    def ds():
        return Dataset.from_blocks(blocks, params=dict(PARAMS))

    ref = lgb.train(dict(PARAMS), ds(), num_boost_round=5)
    base = lgb.train(dict(PARAMS), ds(), num_boost_round=3)
    path = str(tmp_path / "m.txt")
    base.save_model(path)
    res = train_resumable(dict(PARAMS), ds(), 5,
                          checkpoint_dir=str(tmp_path / "ckpt"),
                          checkpoint_rounds=2, resume=True,
                          init_model=path)
    assert res.completed and res.rounds_done == 5
    assert res.resumed_from == path
    assert _trees_equal(ref, res.booster)


# -- daemon: deterministic staleness on the sim clock --------------------


def test_daemon_single_refresh_exact_staleness(tmp_path):
    clock = SimClock()
    d, feed = _daemon(tmp_path, clock, stage_costs=COSTS, slo_ms=10_000.0)
    X, y = _problem()
    feed.push(X, y)                       # arrives at t=0
    clock.advance(0.25)                   # daemon tick latency
    ev = d.tick()
    assert ev["event"] == "flipped" and ev["version"] == "g0001"
    rec = d.tracker.record(1)
    # 4 initial rounds: train leg = dataset_build + 4*train_round = 1.5
    dec = rec.decomposition()
    assert dec["wait"] == 0.25
    assert dec["train"] == COSTS["dataset_build"] + 4 * COSTS["train_round"]
    assert dec["publish"] == COSTS["publish"]
    assert dec["deploy"] == COSTS["deploy"]
    assert dec["flip"] == COSTS["flip"]
    assert ev["staleness_ms"] == 3500.0
    assert d.tracker.worst_staleness_ms() == 3500.0
    assert d.tracker.breaches() == []
    assert d.bank.version("model") == "g0001"
    assert d.tick() is None               # idle once drained
    # a second generation continues the live model, 3 more rounds
    feed.push(*_problem(seed=1))
    ev2 = d.tick()
    assert ev2["event"] == "flipped" and ev2["rounds"] == 7
    assert d.tracker.record(2).decomposition()["train"] == \
        COSTS["dataset_build"] + 3 * COSTS["train_round"]


def test_daemon_slo_breach_is_reported_not_enforced(tmp_path):
    clock = SimClock()
    d, feed = _daemon(tmp_path, clock, stage_costs=COSTS, slo_ms=1_000.0)
    feed.push(*_problem())
    ev = d.tick()
    assert ev["event"] == "flipped"       # the flip still lands
    assert d.tracker.breaches() == [1]
    assert d.snapshot()["staleness"]["breaches"] == [1]


# -- daemon chaos: preemption / corrupt artifact / rollback --------------


def test_daemon_preemption_resumes_from_checkpoint(tmp_path):
    inj = FaultInjector()
    clock = SimClock()
    d, feed = _daemon(tmp_path, clock, injector=inj)
    ctrl, cfeed = _daemon(tmp_path / "ctrl", SimClock())
    for f_, blk in ((feed, 0), (cfeed, 0)):
        f_.push(*_problem(seed=blk))
    assert d.tick()["event"] == "flipped"
    assert ctrl.tick()["event"] == "flipped"
    # gen 2 trains rounds 5..7 (checkpoint cadence 2 -> checkpoint at
    # round 6); hits are global per site, so arm RELATIVE: +2 fires at
    # round 7, after the round-6 checkpoint landed
    inj.arm(FaultSpec(site="continue_train",
                      after=inj.hits["continue_train"] + 2, times=1))
    feed.push(*_problem(seed=1))
    cfeed.push(*_problem(seed=1))
    ev = d.tick()
    assert ev["event"] == "preempted"
    assert d.tracker.record(2).status == "preempted"
    # version N-1 keeps serving from the same state dir while gen N's
    # checkpoint sits on disk (satellite 3)
    assert d.bank.version("model") == "g0001"
    ck = latest_checkpoint(str(tmp_path / "ckpt" / "gen_0002"))
    assert ck is not None and ck.endswith(".lgckpt")
    retry = d.tick()
    assert retry["event"] == "flipped"
    assert str(retry["resumed_from"]).endswith(".lgckpt")
    assert d.tracker.record(2).attempts == 2
    assert ctrl.tick()["event"] == "flipped"
    # preempted-and-resumed converges to the unpreempted flip
    pa = PackedForest.load(d._live_path)
    pb = PackedForest.load(ctrl._live_path)
    for f in ("split_feature", "split_bin", "left", "right",
              "leaf_value", "is_leaf"):
        assert np.array_equal(getattr(pa, f), getattr(pb, f)), f


def test_daemon_corrupt_artifact_rejected_prior_serves(tmp_path):
    inj = FaultInjector()
    d, feed = _daemon(tmp_path, SimClock(), injector=inj)
    feed.push(*_problem())
    assert d.tick()["event"] == "flipped"
    probe = np.random.default_rng(9).normal(size=(16, 5))
    before = d.bank.predict("model", probe)
    inj.arm(FaultSpec(site="artifact_push", after=0, times=1))
    feed.push(*_problem(seed=1))
    ev = d.tick()
    assert ev["event"] == "rejected" and ev["poisoned"]
    assert ev["stage"] == "ingest"        # NaN leaves die at validation
    assert d.bank.version("model") == "g0001"
    assert np.array_equal(before, d.bank.predict("model", probe))
    retry = d.tick()
    assert retry["event"] == "flipped"
    assert d.bank.version("model") == "g0002"


def test_daemon_flip_fault_rolls_back_and_reanchors(tmp_path):
    inj = FaultInjector()
    d, feed = _daemon(tmp_path, SimClock(), injector=inj)
    feed.push(*_problem())
    assert d.tick()["event"] == "flipped"
    probe = np.random.default_rng(9).normal(size=(16, 5))
    before = d.bank.predict("model", probe)
    inj.arm(FaultSpec(site="flip", after=0, times=1))
    feed.push(*_problem(seed=1))
    ev = d.tick()
    assert ev["event"] == "rolled_back"
    assert d.bank.version("model") == "g0001"
    assert np.array_equal(before, d.bank.predict("model", probe))
    assert d.tracker.record(2).status == "rolled_back"
    # next generation re-anchors continuation on the reverted model
    feed.push(*_problem(seed=2))
    nxt = d.tick()
    assert nxt["event"] == "flipped" and nxt["generation"] == 3
    assert nxt["rounds"] == 4 + 3         # initial + one refresh


def test_daemon_poll_fault_never_loses_arrivals(tmp_path):
    inj = FaultInjector()
    d, feed = _daemon(tmp_path, SimClock(), injector=inj)
    feed.push(*_problem())
    inj.arm(FaultSpec(site="data_arrival", after=0, times=1))
    ev = d.tick()
    assert ev["event"] == "poll_fault" and d.poll_faults == 1
    ev = d.tick()                         # retried tick picks them up
    assert ev["event"] == "flipped"


# -- satellite 3: restart re-anchoring + in-progress artifact skip -------


def test_latest_artifact_skips_tmp_and_daemon_reanchors(tmp_path):
    d, feed = _daemon(tmp_path, SimClock())
    feed.push(*_problem())
    assert d.tick()["event"] == "flipped"
    models = d.models_dir
    # a torn publish leaves a .tmp- sibling; it must never be picked up
    open(os.path.join(models, ".tmp-model_g0002.npz"), "wb").close()
    path, gen = latest_artifact(models)
    assert gen == 1 and path.endswith("model_g0001.npz")
    # a fresh daemon over the same state dir re-anchors on g0001
    d2 = RefreshDaemon(PARAMS, str(tmp_path), feed=ArrivalFeed(SimClock()),
                       refresh_rounds=3, initial_rounds=4,
                       clock=SimClock())
    assert d2._gen == 1 and d2._live_rounds == 4
    assert d2.bank.version("model") == "g0001"
    feed2 = d2.feed
    feed2.push(*_problem(seed=1))
    ev = d2.tick()
    assert ev["event"] == "flipped" and ev["version"] == "g0002"
    assert ev["rounds"] == 7
    assert str(ev["resumed_from"]).endswith("model_g0001.npz")


def test_checkpoint_load_latest_skips_tmp(tmp_path):
    X, y = _problem()
    res = train_resumable(dict(PARAMS),
                          Dataset.from_blocks(_blocks(X, y),
                                              params=dict(PARAMS)),
                          4, checkpoint_dir=str(tmp_path),
                          checkpoint_rounds=2)
    real = latest_checkpoint(str(tmp_path))
    assert real is not None
    open(os.path.join(str(tmp_path), ".tmp-ckpt_00000099.lgckpt"),
         "wb").close()
    assert latest_checkpoint(str(tmp_path)) == real


def test_directory_feed_skips_tmp_and_requires_xy(tmp_path):
    X, y = _problem(n=256)
    feed = DirectoryFeed(str(tmp_path), SimClock())
    np.savez(str(tmp_path / "b0.npz"), X=X, y=y)
    open(str(tmp_path / "b1.npz.tmp"), "wb").close()
    got = feed.poll()
    assert len(got) == 1 and got[0].X.shape == (256, 5)
    assert feed.poll() == []              # absorbed once
    np.savez(str(tmp_path / "bad.npz"), Z=X)
    with pytest.raises(ValueError, match="'X' and 'y'"):
        feed.poll()


# -- staleness arithmetic ------------------------------------------------


def test_refresh_record_and_tracker_arithmetic():
    rec = RefreshRecord(generation=1)
    with pytest.raises(ValueError, match="unknown stage"):
        rec.stamp("nope", 0.0)
    for stage, t in zip(("data_arrival", "train_start", "trained",
                         "artifact_saved", "canaried", "serving"),
                        (1.0, 1.5, 3.0, 3.25, 4.25, 4.5)):
        rec.stamp(stage, t)
    assert rec.staleness_s() == 3.5
    dec = rec.decomposition()
    assert dec == {"wait": 0.5, "train": 1.5, "publish": 0.25,
                   "deploy": 1.0, "flip": 0.25, "staleness": 3.5}
    assert rec.as_dict()["staleness_ms"] == 3500.0

    tr = StalenessTracker(slo_ms=2_000.0)
    r1 = tr.begin(1)
    assert tr.begin(1) is r1 and r1.attempts == 2
    r1.stamps.update(rec.stamps)
    r1.status = "serving"
    assert tr.worst_staleness_ms() == 3500.0
    assert tr.breaches() == [1]
    snap = tr.snapshot()
    assert snap["served"] == 1 and snap["slo_ms"] == 2000.0

    clock = SimClock(10.0)
    assert clock() == 10.0 and clock.advance(0.5) == 10.5
    with pytest.raises(ValueError, match="backwards"):
        clock.advance(-1.0)


# -- freshness budgets (wired into default lint) -------------------------


def test_staleness_model_and_budgets_green():
    m = staleness_model()
    for key in ("wait_s", "train_s", "publish_s", "warm_s", "canary_s",
                "flip_s", "staleness_s", "train_frac"):
        assert key in m
    assert m["staleness_s"] > m["train_s"] > 0
    res = check_freshness_budgets()
    assert len(res) == len(FRESHNESS_BUDGETS) == 6
    assert all(r["ok"] for r in res)
    names = {r["name"] for r in res}
    assert {"freshness_slo_ref", "freshness_train_warm_canary_ref",
            "freshness_cold_retrain_blows_slo",
            "freshness_screen_train_leg"} <= names
    # the r20 screened leg reports the factor it applied to the train leg
    screened = next(r for r in res
                    if r["name"] == "freshness_screen_train_leg")
    assert 0.0 < screened["screen_round_factor"] < 1.0
    # the guard-the-model bar: a cold retrain MUST blow the SLO
    cold = freshness_budget_by_name("freshness_cold_retrain_blows_slo")
    assert cold.cmp == "ge" and cold.check()["ok"]
    with pytest.raises(KeyError):
        freshness_budget_by_name("nope")
    sub = check_freshness_budgets(names=["freshness_slo_ref"])
    assert len(sub) == 1 and sub[0]["name"] == "freshness_slo_ref"


# -- satellite 6: task=refresh CLI contract ------------------------------


def _cli_cfg(tmp_path, **over):
    cfg = {"watch_dir": str(tmp_path / "watch"),
           "state_dir": str(tmp_path / "state"),
           "objective": "binary", "num_leaves": "7",
           "learning_rate": "0.2", "max_bin": "31",
           "min_data_in_leaf": "5", "verbose": "-1", "seed": "7",
           "stream_block_rows": "256", "refresh_rounds": "2"}
    cfg.update(over)
    return cfg


def test_refresh_cli_key_validation(tmp_path):
    with pytest.raises(SystemExit, match="watch_dir"):
        _refresh({})
    with pytest.raises(SystemExit, match="state_dir"):
        _refresh({"watch_dir": str(tmp_path)})
    with pytest.raises(SystemExit, match="unknown key"):
        _refresh(_cli_cfg(tmp_path, bogus_knob="1"))
    with pytest.raises(SystemExit, match="integer"):
        _refresh(_cli_cfg(tmp_path, refresh_rounds="five"))
    with pytest.raises(SystemExit, match=">= 1"):
        _refresh(_cli_cfg(tmp_path, max_ticks="0"))
    with pytest.raises(SystemExit, match="staleness_slo_ms"):
        _refresh(_cli_cfg(tmp_path, staleness_slo_ms="-3"))


def test_refresh_cli_misuse_is_typed_not_traceback():
    # flag-style misuse dies with usage, not a KeyError traceback
    with pytest.raises(SystemExit, match="usage"):
        cli_main(["task=refresh", "--help"])
    with pytest.raises(SystemExit, match="refresh"):
        cli_main(["task=refres"])


def test_refresh_cli_end_to_end(tmp_path):
    watch = tmp_path / "watch"
    watch.mkdir()
    X, y = _problem()
    np.savez(str(watch / "block0.npz"), X=X[:256], y=y[:256])
    np.savez(str(watch / "block1.npz"), X=X[256:], y=y[256:])
    out, err = io.StringIO(), io.StringIO()
    assert _refresh(_cli_cfg(tmp_path), stdout=out, stderr=err) == 0
    events = [json.loads(ln) for ln in out.getvalue().splitlines()]
    assert [e["event"] for e in events] == ["flipped"]
    assert events[0]["version"] == "g0001"
    summary = json.loads(err.getvalue())
    assert summary["generation"] == 1 and summary["served"] == 1
    # rerunning the same command line re-anchors and continues
    np.savez(str(watch / "block2.npz"), X=X[:256], y=1.0 - y[:256])
    out2 = io.StringIO()
    assert _refresh(_cli_cfg(tmp_path), stdout=out2,
                    stderr=io.StringIO()) == 0
    ev2 = [json.loads(ln) for ln in out2.getvalue().splitlines()]
    assert ev2[-1]["version"] == "g0002" and ev2[-1]["rounds"] == 4

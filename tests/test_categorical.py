"""Categorical k-vs-rest subset splits (VERDICT r1 item 8; SURVEY.md §7 M4)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def cat_data():
    """Target driven by an UNORDERED category effect: ordered-threshold
    splits need many cuts, one subset split separates it exactly."""
    rng = np.random.default_rng(17)
    n, k = 5000, 30
    cat = rng.integers(0, k, n)
    # alternating category effect: orderings by code are useless
    effect = np.where(cat % 3 == 0, 2.0, np.where(cat % 3 == 1, -2.0, 0.0))
    dense = rng.normal(size=(n, 2)).astype(np.float32)
    y = (effect + 0.3 * dense[:, 0] + rng.normal(0, 0.1, n)).astype(np.float32)
    X = np.column_stack([cat.astype(np.float32), dense])
    return X, y


def test_subset_splits_beat_threshold_splits(cat_data):
    X, y = cat_data
    params = {"objective": "regression", "num_leaves": 15,
              "learning_rate": 0.3, "verbosity": -1, "min_data_in_leaf": 5}
    b_cat = lgb.train(dict(params), lgb.Dataset(X, label=y,
                                                categorical_feature=[0]),
                      num_boost_round=10)
    b_ord = lgb.train(dict(params), lgb.Dataset(X, label=y),
                      num_boost_round=10)
    r_cat = float(np.sqrt(np.mean((b_cat.predict(X) - y) ** 2)))
    r_ord = float(np.sqrt(np.mean((b_ord.predict(X) - y) ** 2)))
    # a %3-pattern category effect is a nightmare for ordered thresholds
    assert r_cat < r_ord * 0.8, (r_cat, r_ord)
    # and it must be genuinely good in absolute terms
    assert r_cat < 0.5, r_cat
    # trees actually contain categorical split nodes
    assert any(bool(np.asarray(t.is_cat_split).any()) for t in b_cat.trees)


def test_categorical_save_load_roundtrip(cat_data, tmp_path):
    X, y = cat_data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(X, label=y, categorical_feature=[0]),
                  num_boost_round=8)
    path = str(tmp_path / "cat.json")
    b.save_model(path)
    b2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(b.predict(X[:300]), b2.predict(X[:300]),
                               rtol=1e-6, atol=1e-7)


def test_unseen_category_goes_right(cat_data):
    X, y = cat_data
    params = {"objective": "regression", "num_leaves": 7, "verbosity": -1,
              "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(X, label=y, categorical_feature=[0]),
                  num_boost_round=5)
    Xq = X[:10].copy()
    Xq[:, 0] = 999.0  # never seen at fit time
    pred = b.predict(Xq)
    assert np.all(np.isfinite(pred))


def test_max_cat_threshold_limits_subset_size(cat_data):
    X, y = cat_data
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "max_cat_threshold": 2}
    b = lgb.train(params, lgb.Dataset(X, label=y, categorical_feature=[0]),
                  num_boost_round=5)
    for t in b.trees:
        icb = np.asarray(t.is_cat_split)
        cm = np.asarray(t.cat_mask)
        for i in np.flatnonzero(icb):
            assert cm[i].sum() <= 2, cm[i].sum()


def test_cv_with_categoricals_runs(cat_data):
    X, y = cat_data
    res = lgb.cv({"objective": "regression", "num_leaves": 15,
                  "verbosity": -1, "min_data_in_leaf": 5},
                 lgb.Dataset(X, label=y, categorical_feature=[0]),
                 num_boost_round=10, nfold=3, early_stopping_rounds=5,
                 stratified=False)
    assert res.best_iter >= 1


def test_frontier_grower_supports_categoricals(cat_data):
    """Wave growth with categorical subset splits: quality must match the
    strict grower's on the unordered-category task."""
    X, y = cat_data
    base = {"objective": "regression", "num_leaves": 31,
            "learning_rate": 0.3, "verbosity": -1, "min_data_in_leaf": 5}
    ds = lambda: lgb.Dataset(X, label=y, categorical_feature=[0])
    b_wave = lgb.train(dict(base, grow_policy="frontier", wave_width=8),
                       ds(), num_boost_round=10)
    b_strict = lgb.train(dict(base, grow_policy="leafwise"), ds(),
                         num_boost_round=10)
    r_wave = float(np.sqrt(np.mean((b_wave.predict(X) - y) ** 2)))
    r_strict = float(np.sqrt(np.mean((b_strict.predict(X) - y) ** 2)))
    assert r_wave < r_strict * 1.2, (r_wave, r_strict)
    assert any(bool(np.asarray(t.is_cat_split).any()) for t in b_wave.trees)

"""Serving resilience: faults, admission control, tenancy, hot swap.

Covers the r12 acceptance surface: deterministic fault injection at
every site (device error mid-predict, corrupt artifact, stalled compile,
clock skew), admission control shedding with typed ``Overloaded``
rejections, heap-ordered deadline expiry, thread-safe stats, and the
ModelBank deploy/swap/rollback lifecycle — including the ingest-
rejection round-trip per corrupted artifact field, where the previous
version must keep serving bit-identically.

Everything runs on mocked/injected clocks and hit-count-triggered
faults: zero sleeps, zero randomness in the failure points.
"""

import io
import json
import os
import signal
import threading

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.serving import (
    FaultError,
    FaultInjector,
    FaultSpec,
    MicroBatcher,
    ModelBank,
    Overloaded,
    PackedForest,
    PredictorRuntime,
    RequestTimeout,
    ServingStats,
    SwapRejected,
    enable_persistent_cache,
    pack_booster,
)

TOL = 1e-6


class _Clock:
    def __init__(self):
        self.t = 0.0

    def __call__(self):
        return self.t


# ---------------------------------------------------------------------------
# fixtures (tiny models, small buckets: CPU compiles dominate wall time)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def served_models(small_regression, tmp_path_factory):
    """(X, booster_v1, v1_path, v2_path): two same-feature-count models
    with DIFFERENT predictions, saved as .npz serving artifacts."""
    X, y = small_regression
    d = tmp_path_factory.mktemp("resilience")
    b1 = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=10)
    b2 = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=np.asarray(X[:, 0], np.float64)),
        num_boost_round=4)
    v1, v2 = str(d / "v1.npz"), str(d / "v2.npz")
    pack_booster(b1).save(v1)
    pack_booster(b2).save(v2)
    return X, b1, v1, v2


@pytest.fixture()
def reg_runtime(served_models):
    _, _, v1, _ = served_models
    return PredictorRuntime(PackedForest.load(v1), max_bucket=64)


def _bank(**kw):
    kw.setdefault("max_bucket", 16)
    kw.setdefault("canary_rows", 4)
    return ModelBank(**kw)


# ---------------------------------------------------------------------------
# fault injector semantics
# ---------------------------------------------------------------------------
def test_fault_spec_semantics():
    inj = FaultInjector()
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("bogus_site")
    with pytest.raises(ValueError, match="unknown fault site"):
        inj.check("bogus_site")
    inj.arm("device_predict", after=2, times=2, message="boom")
    inj.check("device_predict")               # hit 1: clean
    inj.check("device_predict")               # hit 2: clean
    for _ in range(2):                        # hits 3-4: fire
        with pytest.raises(FaultError, match="device_predict: boom"):
            inj.check("device_predict")
    inj.check("device_predict")               # times exhausted: clean
    snap = inj.snapshot()
    assert snap["hits"]["device_predict"] == 5
    assert snap["fired"]["device_predict"] == 2
    inj.disarm_all()
    inj.arm("artifact_load", times=-1)        # -1 = forever
    for _ in range(3):
        with pytest.raises(FaultError):
            inj.check("artifact_load")


def test_fault_compile_stall_and_clock_skew():
    inj = FaultInjector([FaultSpec("compile", stall_s=7.5)])
    assert inj.check("compile") == 7.5        # returned, not raised
    assert inj.check("compile") == 0.0        # single-shot
    clk = _Clock()
    skewed = inj.wrap_clock(clk)
    assert skewed() == 0.0                    # nothing armed: passthrough
    inj.arm("clock", after=inj.hits["clock"], times=-1, skew_s=60.0)
    clk.t = 1.0
    assert skewed() == 61.0                   # every later read skewed
    assert inj.fired["clock"] >= 1


def test_runtime_device_fault_raises_then_recovers(served_models):
    X, _, v1, _ = served_models
    inj = FaultInjector()
    rt = PredictorRuntime(PackedForest.load(v1), max_bucket=16,
                          faults=inj)
    want = rt.predict(X[:4])
    inj.arm("device_predict", message="dropped core")
    with pytest.raises(FaultError, match="dropped core"):
        rt.predict(X[:4])
    assert np.array_equal(rt.predict(X[:4]), want)   # next dispatch fine


def test_microbatcher_fallback_on_device_fault(served_models):
    """A device error mid-predict degrades to the numpy predictor —
    traffic is answered, not errored (and the fault is counted)."""
    X, b, v1, _ = served_models
    inj = FaultInjector([FaultSpec("device_predict", times=1)])
    rt = PredictorRuntime(PackedForest.load(v1), max_bucket=16,
                          faults=inj)
    mb = MicroBatcher(rt, max_batch=4, max_delay_ms=0.0, clock=_Clock())
    hs = [mb.submit(X[i]) for i in range(4)]
    assert mb.pump() == 1
    got = np.array([h.result() for h in hs])
    assert np.abs(got - b.predict(X[:4])).max() <= TOL
    assert rt.stats.snapshot()["fallbacks"] == 4


# ---------------------------------------------------------------------------
# admission control / backpressure
# ---------------------------------------------------------------------------
def test_depth_policy_sheds_typed_overloaded(served_models, reg_runtime):
    X, _, _, _ = served_models
    mb = MicroBatcher(reg_runtime, max_batch=8, max_delay_ms=1e6,
                      clock=_Clock(), max_queue_depth=2,
                      shed_policy="depth")
    h1, h2 = mb.submit(X[0]), mb.submit(X[1])
    h3 = mb.submit(X[2])
    assert h3.done and not h1.done and not h2.done
    with pytest.raises(Overloaded, match="queue full"):
        h3.result()
    assert mb.pending_count() == 2
    snap = reg_runtime.stats.snapshot()
    assert snap["sheds"] >= 1
    mb.flush()
    assert h1.result() is not None and h2.result() is not None


def test_deadline_policy_sheds_predicted_miss(served_models, reg_runtime):
    """With a 10 ms dispatch hint, a 5 ms deadline is predicted dead on
    arrival and sheds; a 50 ms deadline is admitted."""
    X, _, _, _ = served_models
    mb = MicroBatcher(reg_runtime, max_batch=4, max_delay_ms=0.0,
                      clock=_Clock(), shed_policy="deadline",
                      service_time_hint_ms=10.0)
    doomed = mb.submit(X[0], timeout_ms=5.0)
    assert doomed.done
    with pytest.raises(Overloaded, match="predicted queue wait"):
        doomed.result()
    fine = mb.submit(X[1], timeout_ms=50.0)
    assert not fine.done
    assert mb.predicted_wait_s() > 0.0


def test_shed_policy_off_admits_everything(served_models, reg_runtime):
    X, _, _, _ = served_models
    mb = MicroBatcher(reg_runtime, max_batch=8, max_delay_ms=1e6,
                      clock=_Clock(), max_queue_depth=2,
                      shed_policy="off", service_time_hint_ms=100.0)
    hs = [mb.submit(X[i], timeout_ms=0.001) for i in range(5)]
    assert not any(h.done for h in hs)        # nothing shed
    assert mb.pending_count() == 5


def test_deadline_model_inactive_under_mocked_clock(served_models,
                                                    reg_runtime):
    """Default policy + mocked clock (dt == 0 dispatches): the EWMA
    stays 0 and the predictor never sheds — the r6-era tests' contract."""
    X, _, _, _ = served_models
    mb = MicroBatcher(reg_runtime, max_batch=2, max_delay_ms=0.0,
                      clock=_Clock(), timeout_ms=0.01)
    hs = [mb.submit(X[i]) for i in range(4)]
    assert not any(h.done for h in hs)
    mb.pump()
    assert all(h.done for h in hs)
    assert mb.predicted_wait_s() == 0.0


def test_ewma_learns_dispatch_time_through_clock(served_models,
                                                 reg_runtime):
    X, _, _, _ = served_models

    class _Ticking(_Clock):
        def __call__(self):
            self.t += 0.001               # every read advances 1 ms
            return self.t

    mb = MicroBatcher(reg_runtime, max_batch=2, max_delay_ms=0.0,
                      clock=_Ticking())
    mb.submit(X[0])
    mb.submit(X[1])
    mb.pump()
    assert mb.predicted_wait_s() > 0.0    # measured a nonzero dispatch


def test_invalid_admission_config_rejected(reg_runtime):
    with pytest.raises(ValueError, match="shed_policy"):
        MicroBatcher(reg_runtime, shed_policy="sometimes")
    with pytest.raises(ValueError, match="max_queue_depth"):
        MicroBatcher(reg_runtime, max_queue_depth=0)


# ---------------------------------------------------------------------------
# heap-ordered deadline expiry
# ---------------------------------------------------------------------------
def test_heap_expiry_pops_only_due_requests(served_models, reg_runtime):
    """30 staggered deadlines; advancing past 15 of them expires exactly
    those 15 (heap pops, no whole-queue scan) and the remainder serve in
    order."""
    X, b, _, _ = served_models
    clk = _Clock()
    mb = MicroBatcher(reg_runtime, max_batch=64, max_delay_ms=1e6,
                      clock=clk)
    hs = [mb.submit(X[i], timeout_ms=float(i + 1)) for i in range(30)]
    t0 = reg_runtime.stats.snapshot()["timeouts"]
    clk.t = 0.0155                        # deadlines 1..15 ms are due
    assert mb.pump() == 0
    assert reg_runtime.stats.snapshot()["timeouts"] - t0 == 15
    assert mb.pending_count() == 15
    assert not mb._exp_heap or mb._exp_heap[0][0] >= clk.t
    mb.flush()
    for i, h in enumerate(hs):
        if i < 15:
            with pytest.raises(RequestTimeout):
                h.result()
        else:
            assert abs(h.result() - b.predict(X[i:i + 1])[0]) <= TOL


def test_expiry_tombstones_never_double_count(served_models, reg_runtime):
    X, _, _, _ = served_models
    clk = _Clock()
    mb = MicroBatcher(reg_runtime, max_batch=4, max_delay_ms=1e6,
                      clock=clk)
    mb.submit(X[0], timeout_ms=1.0)
    hs = [mb.submit(X[i], timeout_ms=1e6) for i in range(1, 5)]
    clk.t = 0.002
    mb.pump()                             # expires 1, dispatches the 4
    assert all(h.done for h in hs)
    assert mb.pending_count() == 0
    assert mb.pump() == 0 and mb.flush() == 0     # queue + heap drained


def test_clock_skew_fault_drives_expiry(served_models, reg_runtime):
    """The ``clock`` fault site: a skew injected between submit and pump
    expires in-queue requests — time discontinuities degrade to typed
    timeouts, not wrong answers."""
    X, _, _, _ = served_models
    inj = FaultInjector()
    clk = _Clock()
    mb = MicroBatcher(reg_runtime, max_batch=8, max_delay_ms=1e6,
                      timeout_ms=5.0, clock=inj.wrap_clock(clk))
    h = mb.submit(X[0])
    inj.arm("clock", after=inj.hits["clock"], times=-1, skew_s=60.0)
    mb.pump()
    with pytest.raises(RequestTimeout):
        h.result()


# ---------------------------------------------------------------------------
# stats under concurrent writers
# ---------------------------------------------------------------------------
def test_stats_concurrent_writers_exact_counts():
    stats = ServingStats()
    n, workers = 500, 8
    errors = []

    def hammer(k):
        try:
            for i in range(n):
                stats.record_request()
                stats.record_dispatch(bucket=1 << (k % 4), rows=1,
                                      padded=1, latency_s=1e-4)
                stats.record_cache(bucket=1 << (k % 4), hit=i % 2 == 0)
                stats.record_shed()
                stats.record_timeout()
                stats.record_fallback()
                stats.record_batch(queue_latency_s=1e-4)
                if i % 50 == 0:
                    json.dumps(stats.snapshot())   # reader mid-write
        except Exception as e:                     # noqa: BLE001
            errors.append(e)

    ts = [threading.Thread(target=hammer, args=(k,))
          for k in range(workers)]
    for t in ts:
        t.start()
    for t in ts:
        t.join()
    assert not errors
    snap = stats.snapshot()
    total = n * workers
    assert snap["requests"] == total
    assert snap["sheds"] == total
    assert snap["timeouts"] == total
    assert snap["fallbacks"] == total
    assert snap["batched_dispatches"] == total
    assert sum(b["dispatches"] for b in snap["buckets"]) == total
    assert sum(b["rows"] for b in snap["buckets"]) == total
    hits = sum(b["cache_hits"] for b in snap["buckets"])
    misses = sum(b["cache_misses"] for b in snap["buckets"])
    assert hits + misses == total


# ---------------------------------------------------------------------------
# ModelBank: tenancy, hot swap, rollback
# ---------------------------------------------------------------------------
def test_bank_deploy_predict_and_snapshot(served_models):
    X, b, v1, _ = served_models
    bank = _bank()
    rep = bank.deploy("m", v1)
    assert rep["ok"] and rep["version"] == "v1"
    assert rep["canary"]["rows"] == 4
    assert np.abs(bank.predict("m", X[:20]) - b.predict(X[:20])).max() \
        <= TOL
    assert bank.names() == ["m"] and bank.version("m") == "v1"
    snap = bank.snapshot()
    assert snap["models"]["m"]["deploys"] == 1
    assert snap["models"]["m"]["swap_history"][-1]["stage"] == "flipped"
    json.dumps(snap)
    with pytest.raises(KeyError, match="no model"):
        bank.runtime("ghost")


_CORRUPTIONS = {
    "cycle": lambda p: p.left.__setitem__((0, 0), 0),
    "dangling": lambda p: p.left.__setitem__((0, 0),
                                             p.left.shape[1] + 9),
    "bad_feature": lambda p: p.split_feature.__setitem__(
        (0, 0), p.num_feature() + 3),
    "nonfinite_leaf": lambda p: p.leaf_value.__setitem__(
        (0, int(np.argmax(p.is_leaf[0]))), np.nan),
}


@pytest.mark.parametrize("field", sorted(_CORRUPTIONS))
def test_ingest_rejection_rollback_roundtrip(served_models, tmp_path,
                                             field):
    """Satellite 4: corrupt each validated field, attempt the swap, and
    assert the PREVIOUS version keeps serving bit-identically."""
    import copy

    X, _, v1, _ = served_models
    bank = _bank()
    bank.deploy("m", v1)
    probe = X[:16]
    baseline = bank.predict("m", probe)

    bad = copy.deepcopy(PackedForest.load(v1))
    _CORRUPTIONS[field](bad)
    bad_path = str(tmp_path / f"bad_{field}.npz")
    bad.save(bad_path)                    # save() does not re-validate
    with pytest.raises(SwapRejected) as ei:
        bank.deploy("m", bad_path)
    assert ei.value.stage == "ingest"
    assert bank.version("m") == "v1"
    assert np.array_equal(bank.predict("m", probe), baseline)
    hist = bank.snapshot()["models"]["m"]["swap_history"]
    assert hist[-1]["ok"] is False and "error" in hist[-1]


def test_bank_feature_count_mismatch_rejected(served_models, tmp_path):
    X, _, v1, _ = served_models
    bank = _bank()
    bank.deploy("m", v1)
    rng = np.random.default_rng(0)
    Xw = rng.normal(size=(300, X.shape[1] + 2))
    bw = lgb.train({"objective": "regression", "num_leaves": 7,
                    "verbosity": -1},
                   lgb.Dataset(Xw, label=Xw[:, 0]), num_boost_round=3)
    wide = str(tmp_path / "wide.npz")
    pack_booster(bw).save(wide)
    with pytest.raises(SwapRejected, match="feature count changed"):
        bank.deploy("m", wide)
    assert bank.version("m") == "v1"


def test_bank_artifact_load_fault_rejects(served_models):
    _, _, v1, _ = served_models
    inj = FaultInjector()
    bank = _bank(faults=inj)
    bank.deploy("m", v1)
    baseline_rt = bank.runtime("m")
    inj.arm("artifact_load", message="disk ate the npz")
    with pytest.raises(SwapRejected, match="disk ate the npz"):
        bank.deploy("m", v1)
    assert bank.runtime("m") is baseline_rt


def test_bank_canary_catches_device_fault(served_models):
    """A device fault during the post-build canary rejects the swap —
    the new runtime never sees traffic, the old one never stopped."""
    X, _, v1, v2 = served_models
    inj = FaultInjector()
    bank = _bank(faults=inj)
    bank.deploy("m", v1)
    baseline = bank.predict("m", X[:8])
    inj.arm("device_predict", times=-1, message="canary died")
    with pytest.raises(SwapRejected) as ei:
        bank.deploy("m", v2)
    assert ei.value.stage == "canary"
    inj.disarm_all()
    assert bank.version("m") == "v1"
    assert np.array_equal(bank.predict("m", X[:8]), baseline)


def test_bank_stalled_compile_aborts_swap(served_models):
    _, _, v1, v2 = served_models
    inj = FaultInjector()
    bank = _bank(faults=inj, compile_timeout_s=0.5, clock=_Clock(),
                 canary_rows=0)
    bank.deploy("m", v1)                  # clean: 0 elapsed on the mock
    inj.arm("compile", stall_s=10.0)
    with pytest.raises(SwapRejected, match="compile stalled"):
        bank.deploy("m", v2)
    assert bank.version("m") == "v1"


def test_bank_hot_swap_atomic_for_queued_traffic(served_models):
    """Requests queued BEFORE the flip dispatch on the runtime resolved
    AT dispatch time — the bank-provider MicroBatcher is the swap point,
    and nothing in flight errors."""
    X, _, v1, v2 = served_models
    bank = _bank()
    bank.deploy("m", v1)
    v2_ref = PredictorRuntime(PackedForest.load(v2), max_bucket=16)
    mb = bank.batcher("m", max_batch=4, max_delay_ms=0.0, clock=_Clock())
    hs = [mb.submit(X[i]) for i in range(3)]
    bank.deploy("m", v2)                  # flip while 3 are queued
    assert mb.pump() == 1
    got = np.array([h.result() for h in hs])
    assert np.array_equal(got, v2_ref.predict(X[:3]))   # served on v2
    with pytest.raises(KeyError):
        bank.batcher("ghost")


def test_bank_rollback_bit_identical(served_models):
    X, _, v1, v2 = served_models
    bank = _bank()
    bank.deploy("m", v1)
    probe = X[:16]
    baseline = bank.predict("m", probe)
    bank.deploy("m", v2)
    assert bank.version("m") == "v2"
    assert not np.array_equal(bank.predict("m", probe), baseline)
    rep = bank.rollback("m")
    assert rep["version"] == "v1"
    # the v1 runtime (and compiled programs) never went away: outputs
    # are byte-for-byte the pre-swap ones
    assert np.array_equal(bank.predict("m", probe), baseline)
    bank.rollback("m")                    # flip-flop back to v2
    assert bank.version("m") == "v2"


def test_bank_rollback_without_previous_rejected(served_models):
    _, _, v1, _ = served_models
    bank = _bank()
    bank.deploy("m", v1)
    with pytest.raises(SwapRejected, match="no previous version"):
        bank.rollback("m")


def test_bank_multi_tenancy_isolated_stats(served_models):
    X, _, v1, v2 = served_models
    bank = _bank()
    bank.deploy("a", v1)
    bank.deploy("b", v2)
    bank.predict("a", X[:4])
    snap = bank.snapshot()
    a, b = snap["models"]["a"]["stats"], snap["models"]["b"]["stats"]
    assert sum(e["dispatches"] for e in a["buckets"]) >= 1
    assert sum(e["dispatches"] for e in b["buckets"]) == 1   # canary only
    assert sorted(snap["models"]) == ["a", "b"]


# ---------------------------------------------------------------------------
# warm restarts: manifest + persistent compile cache
# ---------------------------------------------------------------------------
def test_warm_manifest_roundtrip(served_models, tmp_path):
    X, _, v1, _ = served_models
    bank = _bank(max_bucket=8, warm_on_deploy=True)
    bank.deploy("m", v1)
    want = bank.predict("m", X[:8])
    manifest = str(tmp_path / "warm.json")
    bank.save_warm_manifest(manifest)

    bank2 = _bank(max_bucket=8)
    rep = bank2.restore_warm_manifest(manifest)
    assert rep["models"] == 1 and rep["skipped"] == []
    rt2 = bank2.runtime("m")
    assert len(rt2._cache) == len(rt2.buckets)     # ladder is warm
    n = rt2.num_compiles
    got = bank2.predict("m", X[:8])
    assert rt2.num_compiles == n                   # zero traffic compiles
    assert np.abs(got - want).max() <= TOL
    assert bank2.version("m") == "v1"


def test_warm_manifest_version_gate(tmp_path):
    p = str(tmp_path / "future.json")
    with open(p, "w") as f:
        json.dump({"format_version": 99, "models": []}, f)
    with pytest.raises(ValueError, match="newer than supported"):
        _bank().restore_warm_manifest(p)


def test_enable_persistent_cache_configures_jax(tmp_path):
    import jax

    assert enable_persistent_cache(str(tmp_path / "jaxcache")) is True
    try:
        assert jax.config.jax_compilation_cache_dir == \
            str(tmp_path / "jaxcache")
    finally:
        jax.config.update("jax_compilation_cache_dir", None)


# ---------------------------------------------------------------------------
# serve CLI: key validation, control lines, SIGTERM drain
# ---------------------------------------------------------------------------
def _run_serve(path, cfg, lines):
    from lightgbm_tpu.__main__ import _serve

    out, err = io.StringIO(), io.StringIO()
    rc = _serve(path, dict(cfg), stdin=iter(lines), stdout=out,
                stderr=err)
    return rc, out.getvalue().splitlines(), err.getvalue()


def test_cli_serve_rejects_unknown_and_invalid_keys(served_models):
    from lightgbm_tpu.__main__ import _serve

    _, _, v1, _ = served_models
    for cfg, msg in (
            ({"max_queue_dpeth": "4"}, "unknown key"),
            ({"shed_policy": "sometimes"}, "shed_policy"),
            ({"max_queue_depth": "0"}, "max_queue_depth"),
            ({"max_queue_depth": "lots"}, "max_queue_depth"),
            ({"canary_rows": "-1"}, "canary_rows"),
    ):
        with pytest.raises(SystemExit, match=msg):
            _serve(v1, cfg, stdin=iter(()), stdout=io.StringIO(),
                   stderr=io.StringIO())


def test_cli_serve_control_lines_swap_rollback_stats(served_models):
    X, _, v1, v2 = served_models
    row = ",".join(f"{x:.8g}" for x in X[0])
    # max_batch=1: each row dispatches (and binds to the ACTIVE version)
    # before the next control line is read
    rc, out, err = _run_serve(v1, {"canary_rows": "4",
                                   "max_batch": "1"}, [
        f"{row}\n",
        "!stats\n",
        f"!swap {v2}\n",
        f"{row}\n",
        "!rollback\n",
        f"{row}\n",
        "!frobnicate\n",
    ])
    assert rc == 0
    assert len(out) == 3
    assert out[0] != out[1]               # v2 answers differently
    assert out[0] == out[2]               # rollback restores exactly
    assert "swapped default -> v2" in err
    assert "rolled back default -> v1" in err
    assert "unknown control" in err
    stats_line = [ln for ln in err.splitlines()
                  if ln.startswith("{")][0]
    assert "requests" in json.loads(stats_line)


def test_cli_serve_rejected_swap_keeps_serving(served_models, tmp_path):
    import copy

    X, _, v1, _ = served_models
    bad = copy.deepcopy(PackedForest.load(v1))
    _CORRUPTIONS["cycle"](bad)
    bad_path = str(tmp_path / "bad.npz")
    bad.save(bad_path)
    row = ",".join(f"{x:.8g}" for x in X[0])
    rc, out, err = _run_serve(v1, {}, [
        f"{row}\n",
        f"!swap {bad_path}\n",
        f"{row}\n",
    ])
    assert rc == 0
    assert out[0] == out[1]               # old version never blinked
    assert "swap rejected at ingest" in err


def test_cli_serve_sigterm_drains_gracefully(served_models):
    """SIGTERM mid-stream: stop admitting, flush in-flight, final stats
    snapshot — the admitted requests are answered, the post-signal line
    is not."""
    X, _, v1, _ = served_models
    rows = [",".join(f"{x:.8g}" for x in X[i]) for i in range(3)]

    def feed():
        yield rows[0] + "\n"
        yield rows[1] + "\n"
        signal.raise_signal(signal.SIGTERM)
        yield rows[2] + "\n"              # read while draining: dropped

    rc, out, err = _run_serve(v1, {}, feed())
    assert rc == 0
    assert len(out) == 2                  # both admitted requests answered
    assert "ERROR" not in "".join(out)
    assert "drained on SIGTERM" in err
    final = json.loads(err.splitlines()[-1])
    assert final["requests"] == 2
    # the process-level handler is restored after the drain
    assert signal.getsignal(signal.SIGTERM) != signal.SIG_IGN


# ---------------------------------------------------------------------------
# SLO budget models (pure arithmetic; also run in the default lint pass)
# ---------------------------------------------------------------------------
def test_serve_queue_model_regimes():
    from lightgbm_tpu.analysis.budgets import serve_queue_model

    stable = serve_queue_model(1000.0, dispatch_ms=2.0, max_batch=128)
    assert stable["utilization"] < 1.0
    assert stable["miss_frac"] == 0.0 and stable["shed_frac"] == 0.0
    over_off = serve_queue_model(2 * 64000.0, 2.0, shed_policy="off")
    assert over_off["miss_frac"] == 1.0 and over_off["shed_frac"] == 0.0
    over_on = serve_queue_model(2 * 64000.0, 2.0, shed_policy="deadline")
    assert over_on["miss_frac"] == 0.0
    assert abs(over_on["shed_frac"] - 0.5) < 1e-9   # 1 - 1/util at 2x
    assert abs(over_on["served_frac"] - 0.5) < 1e-9


def test_serve_fault_p99_capped_by_shedding():
    from lightgbm_tpu.analysis.budgets import serve_fault_p99_model

    shed = serve_fault_p99_model(shedding=True)
    unshed = serve_fault_p99_model(shedding=False)
    assert shed["fault_p99_ms"] < unshed["fault_p99_ms"]
    assert shed["fault_p99_ms"] == pytest.approx(52.0)   # deadline+dispatch
    assert shed["inflation_x"] <= 8.0


def test_serve_slo_budgets_all_green_and_wired():
    from lightgbm_tpu.analysis.budgets import (SERVE_SLO_BUDGETS,
                                               check_serve_slo_budgets,
                                               serve_slo_budget_by_name)

    res = check_serve_slo_budgets()
    assert len(res) == len(SERVE_SLO_BUDGETS) == 12
    assert all(r["ok"] for r in res)
    names = {r["name"] for r in res}
    assert {"serve_shed_before_miss", "serve_fault_p99_inflation",
            "serve_int8_models_per_byte", "serve_dp_speedup_d4",
            "serve_fused_launch_drop", "serve_fused_vmem_int8",
            "serve_fused_no_f32_table_int8"} \
        <= names
    assert serve_slo_budget_by_name(
        "serve_shed_before_miss").check()["ok"]
    with pytest.raises(KeyError):
        serve_slo_budget_by_name("nope")

"""Frontier (wave) grower: parity with the strict grower + semantics.

The frontier grower (models/tree.py grow_tree_frontier) is the large-data
fast path: up to wave_width splits per histogram pass, sibling histograms
derived by subtraction (LightGBM's ConstructHistogram trick — SURVEY.md
§3.1).  With wave_width=1 its split order equals strict best-first, so we
check exact structural parity there; for wider waves we check predictive
parity and invariants.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import lightgbm_tpu as lgb
from lightgbm_tpu.models.tree import grow_tree, grow_tree_frontier
from lightgbm_tpu.ops.predict import predict_tree_binned
from lightgbm_tpu.ops.split import SplitContext


def make_ctx(min_data=1.0):
    z = jnp.float32
    return SplitContext(lambda_l1=z(0.0), lambda_l2=z(0.0),
                        min_data_in_leaf=z(min_data),
                        min_sum_hessian=z(0.0), min_gain_to_split=z(0.0))


def _stats(y):
    n = len(y)
    return jnp.stack([jnp.asarray(-y, jnp.float32),
                      jnp.ones(n, jnp.float32),
                      jnp.ones(n, jnp.float32)], axis=-1)


def _problem(n=3000, f=5, bins_per=32, seed=0):
    rng = np.random.default_rng(seed)
    bins = rng.integers(0, bins_per, (n, f)).astype(np.uint8)
    y = (1.5 * bins[:, 0] - 0.3 * (bins[:, 1] > 12) * bins[:, 2]
         + 0.05 * rng.normal(0, 1, n)).astype(np.float32)
    y = (y - y.mean()) / y.std()
    return bins, y


def test_wave1_matches_strict_structure():
    bins, y = _problem()
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    t_strict, rl_strict = grow_tree(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(), 15, 32, -1)
    t_wave, rl_wave = grow_tree_frontier(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(), 15, 32, -1,
        wave_width=1)
    assert int(t_wave.num_leaves) == int(t_strict.num_leaves)
    np.testing.assert_array_equal(np.asarray(t_wave.split_feature),
                                  np.asarray(t_strict.split_feature))
    np.testing.assert_array_equal(np.asarray(t_wave.split_bin),
                                  np.asarray(t_strict.split_bin))
    np.testing.assert_array_equal(np.asarray(rl_wave), np.asarray(rl_strict))
    np.testing.assert_allclose(np.asarray(t_wave.leaf_value),
                               np.asarray(t_strict.leaf_value), atol=1e-4)


@pytest.mark.parametrize("width", [4, 42])
def test_wide_wave_predictive_parity(width):
    bins, y = _problem(seed=1)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    t_strict, rl_s = grow_tree(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(min_data=20.0),
        31, 32, -1)
    t_wave, rl_w = grow_tree_frontier(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(min_data=20.0),
        31, 32, -1, wave_width=width)
    assert int(t_wave.num_leaves) <= 31
    mse_s = float(np.mean((np.asarray(t_strict.leaf_value)[rl_s] - y) ** 2))
    mse_w = float(np.mean((np.asarray(t_wave.leaf_value)[rl_w] - y) ** 2))
    # one tree's fit quality must match strict within a whisker
    assert mse_w <= mse_s * 1.1 + 1e-6


def test_wave_traversal_matches_row_leaf():
    bins, y = _problem(seed=2)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    tree, row_leaf = grow_tree_frontier(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(), 31, 32, -1,
        wave_width=8)
    vals_train = np.asarray(tree.leaf_value)[np.asarray(row_leaf)]
    vals_traverse = np.asarray(
        predict_tree_binned(tree, jnp.asarray(bins), max_depth_cap=31))
    np.testing.assert_allclose(vals_train, vals_traverse, atol=1e-6)


def test_wave_min_data_and_budget():
    bins, y = _problem(seed=3)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    tree, row_leaf = grow_tree_frontier(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(min_data=100.0),
        16, 32, -1, wave_width=8)
    leaves = np.asarray(row_leaf)
    is_leaf = np.asarray(tree.is_leaf)
    assert int(tree.num_leaves) <= 16
    for node in np.unique(leaves):
        assert is_leaf[node]
        assert (leaves == node).sum() >= 100


def test_wave_max_depth():
    bins, y = _problem(seed=4)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    tree, _ = grow_tree_frontier(
        jnp.asarray(bins), _stats(y), fmask, make_ctx(), 31, 32,
        max_depth=2, wave_width=8)
    assert int(tree.num_leaves) <= 4


def test_frontier_policy_end_to_end_quality(small_regression):
    """Full train() with grow_policy=frontier lands near the strict model."""
    X, y = small_regression
    params = dict(objective="regression", learning_rate=0.1, num_leaves=31,
                  min_data_in_leaf=20, verbosity=-1)
    ds = lgb.Dataset(X, label=y)
    b_strict = lgb.train({**params, "grow_policy": "leafwise"}, ds,
                         num_boost_round=50)
    b_wave = lgb.train({**params, "grow_policy": "frontier"},
                       lgb.Dataset(X, label=y), num_boost_round=50)
    rmse_s = float(np.sqrt(np.mean((b_strict.predict(X) - y) ** 2)))
    rmse_w = float(np.sqrt(np.mean((b_wave.predict(X) - y) ** 2)))
    assert rmse_w <= rmse_s * 1.05 + 1e-6


def test_frontier_deterministic(small_regression):
    X, y = small_regression
    params = dict(objective="regression", num_leaves=31, seed=7,
                  grow_policy="frontier", bagging_fraction=0.8,
                  bagging_freq=1, feature_fraction=0.8, verbosity=-1)
    p1 = lgb.train(params, lgb.Dataset(X, label=y), 20).predict(X)
    p2 = lgb.train(params, lgb.Dataset(X, label=y), 20).predict(X)
    np.testing.assert_array_equal(p1, p2)


def test_fused_goss_matches_host_loop():
    """update_many's scanned GOSS path == per-round host GOSS updates."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(0)
    n = 4000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3)
         + rng.normal(0, 0.1, n)).astype(np.float32)
    params = {"boosting": "goss", "objective": "regression",
              "num_leaves": 15, "learning_rate": 0.2, "verbosity": -1,
              "top_rate": 0.3, "other_rate": 0.2}
    host = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=10, callbacks=[lambda env: None])
    fused = lgb.train(dict(params), lgb.Dataset(X, label=y),
                      num_boost_round=10)
    for th, tf in zip(host.trees, fused.trees):
        np.testing.assert_array_equal(np.asarray(th.split_feature),
                                      np.asarray(tf.split_feature))
    np.testing.assert_allclose(host.predict(X), fused.predict(X),
                               rtol=1e-5, atol=1e-6)

"""Fused on-device cv: parity with the host-loop cv path."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.config import parse_params
from lightgbm_tpu.models.fused import fused_cv_eligible, run_fused_cv_batch


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(21)
    n = 3000
    X = rng.normal(0, 1, (n, 5))
    y = X[:, 0] + np.sin(2 * X[:, 1]) + 0.3 * X[:, 2] * X[:, 3] \
        + 0.1 * rng.normal(0, 1, n)
    return X, y


def test_fused_cv_close_to_host_cv(reg_data):
    X, y = reg_data
    dtrain = lgb.Dataset(X, label=y)
    params = {"objective": "regression", "learning_rate": 0.1,
              "num_leaves": 15, "verbosity": 0}
    fused = lgb.cv(params, dtrain, num_boost_round=60, nfold=4,
                   early_stopping_rounds=5, seed=7, stratified=False)
    host = lgb.cv(params, dtrain, num_boost_round=60, nfold=4,
                  early_stopping_rounds=5, seed=7, stratified=False,
                  eval_train_metric=True)  # forces the host path
    assert "valid l2-mean" in fused and "valid l2-mean" in host
    # same fold split, same deterministic grower -> same history
    k = min(len(fused["valid l2-mean"]), len(host["valid l2-mean"]))
    np.testing.assert_allclose(fused["valid l2-mean"][:k],
                               host["valid l2-mean"][:k], rtol=2e-4)
    assert abs(fused.best_iter - host.best_iter) <= 1
    assert fused.best_score == pytest.approx(host.best_score, rel=2e-3)


def test_fused_cv_early_stops(reg_data):
    X, y = reg_data
    dtrain = lgb.Dataset(X, label=y)
    fit = lgb.cv({"objective": "regression", "learning_rate": 0.5,
                  "num_leaves": 31, "verbosity": 0}, dtrain,
                 num_boost_round=500, nfold=3, early_stopping_rounds=3,
                 seed=3, stratified=False)
    # aggressive lr overfits fast; must stop well before 500
    assert len(fit["valid l2-mean"]) < 400
    assert fit.best_score < 0  # sign-flipped (higher is better)


def test_fused_cv_batch_multiple_configs(reg_data):
    X, y = reg_data
    dtrain = lgb.Dataset(X, label=y)
    dtrain.construct()
    base = {"objective": "regression", "num_leaves": 15, "verbosity": 0}
    cfgs = [parse_params({**base, "learning_rate": lr,
                          "min_data_in_leaf": md})
            for lr, md in [(0.3, 20), (0.1, 20), (0.1, 40)]]
    rng = np.random.default_rng(0)
    n = dtrain.num_data()
    assign = rng.permutation(n) % 3
    fold_masks = np.stack([assign != k for k in range(3)])
    hist, best_iter, best_raw, rounds, metric = run_fused_cv_batch(
        dtrain, cfgs, fold_masks, num_boost_round=40,
        early_stopping_rounds=5, seed=1)
    assert hist.shape == (40, 3, 3)
    assert metric == "l2"
    assert (best_iter >= 1).all() and (best_iter <= 40).all()
    # each config's recorded best matches its own history
    for c in range(3):
        means = np.nanmean(hist[:, c, :], axis=1)
        assert best_raw[c] == pytest.approx(np.nanmin(means[:rounds]),
                                            rel=1e-5)
    # single-config fused runs must agree with the batch
    h1, bi1, br1, _, _ = run_fused_cv_batch(
        dtrain, cfgs[1:2], fold_masks, num_boost_round=40,
        early_stopping_rounds=5, seed=1)
    np.testing.assert_allclose(np.nanmean(h1[:, 0], axis=1)[:10],
                               np.nanmean(hist[:, 1], axis=1)[:10],
                               rtol=2e-4)


def test_fused_eligibility_gates():
    p = parse_params({"objective": "regression"})
    assert fused_cv_eligible(p, None, None)
    assert not fused_cv_eligible(p, lambda *a: None, None)
    p2 = parse_params({"objective": "regression", "metric": ["l2", "l1"]})
    assert not fused_cv_eligible(p2, None, None)
    p3 = parse_params({"objective": "regression", "boosting": "rf"})
    assert not fused_cv_eligible(p3, None, None)


def test_fused_cv_categorical_matches_host_loop():
    """Categorical datasets are fused-cv eligible (VERDICT r2 item 6): the
    batched program threads cat_key, and its result must match the host
    cv loop exactly (same RNG lockstep)."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.fused import fused_cv_eligible

    rng = np.random.default_rng(31)
    n, k = 3000, 16
    cat = rng.integers(0, k, n)
    # distinct per-category effects (tied effects make the ratio-sort order
    # summation-order-dependent and fused/host pick different tied subsets)
    effect = rng.normal(0, 1.2, k)[cat]
    dense = rng.normal(size=(n, 2)).astype(np.float32)
    y = (effect + 0.4 * dense[:, 0] + rng.normal(0, 0.1, n)).astype(np.float32)
    X = np.column_stack([cat.astype(np.float32), dense])
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "min_data_in_leaf": 5, "learning_rate": 0.2}

    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    ds.construct()
    assert fused_cv_eligible(parse_params(params), None, None, ds)

    fused = lgb.cv(dict(params), ds, num_boost_round=12, nfold=3, seed=11)
    # a no-op callback forces the host cv loop (fused path disallows hooks)
    host = lgb.cv(dict(params), ds, num_boost_round=12, nfold=3, seed=11,
                  callbacks=[lambda env: None])
    # near-tie category subsets can flip between the batched and host
    # programs (different f32 summation order in the wide vs skinny
    # histogram matmuls) — the histories must agree to ~1e-3, not bitwise
    np.testing.assert_allclose(fused["valid l2-mean"], host["valid l2-mean"],
                               rtol=2e-3, atol=1e-5)


def test_fused_cv_min_delta_matches_host_loop():
    """early_stopping_min_delta is fused-cv eligible (r3 weak #7): the
    tolerance rides the on-device improvement compare as a traced
    per-config scalar, so a coarse min_delta must stop the fused run at
    the same round the host callback loop stops."""
    import lightgbm_tpu as lgb
    from lightgbm_tpu.config import parse_params
    from lightgbm_tpu.models.fused import fused_cv_eligible

    rng = np.random.default_rng(5)
    n = 2000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] - 0.5 * X[:, 1] ** 2
         + rng.normal(0, 0.3, n)).astype(np.float32)
    params = {"objective": "regression", "num_leaves": 15, "verbosity": -1,
              "learning_rate": 0.3, "early_stopping_min_delta": 0.02}
    assert fused_cv_eligible(parse_params(params), None, None)

    fused = lgb.cv(dict(params), lgb.Dataset(X, label=y),
                   num_boost_round=60, nfold=3, seed=7,
                   early_stopping_rounds=3)
    host = lgb.cv(dict(params), lgb.Dataset(X, label=y),
                  num_boost_round=60, nfold=3, seed=7,
                  early_stopping_rounds=3, callbacks=[lambda env: None])
    # the tolerance-gated STOPPING ROUND is the semantics under test; the
    # per-round values carry the known fused-vs-host f32 summation-order
    # difference (wide vs skinny histogram matmuls), same as the other
    # fused parity tests
    assert len(fused["valid l2-mean"]) == len(host["valid l2-mean"])
    np.testing.assert_allclose(fused["valid l2-mean"], host["valid l2-mean"],
                               rtol=2e-3, atol=1e-5)

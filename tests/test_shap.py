"""pred_contrib (exact path-dependent TreeSHAP) — ops/shap.py.

Ground truth: brute-force Shapley enumeration over all feature subsets,
with the conditional expectation defined EXACTLY as path-dependent
TreeSHAP does (follow x for features in S, split by cover fractions
otherwise).  Small feature counts keep 2^F enumeration cheap.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def _tree_cond_expect(t, bins_row, subset):
    """E[tree | features in `subset` follow x] under cover-fraction
    weighting — the defining recursion of path-dependent TreeSHAP."""
    def rec(node):
        if t["is_leaf"][node]:
            return float(t["leaf_value"][node])
        feat = int(t["split_feature"][node])
        left, right = int(t["left"][node]), int(t["right"][node])
        if feat in subset:
            code = int(bins_row[feat])
            if t.get("is_cat_split") is not None and t["is_cat_split"][node]:
                go_left = bool(t["cat_mask"][node][code])
            else:
                go_left = code <= int(t["split_bin"][node])
            return rec(left if go_left else right)
        denom = max(float(t["count"][node]), 1e-12)
        wl = float(t["count"][left]) / denom
        wr = float(t["count"][right]) / denom
        return wl * rec(left) + wr * rec(right)

    return rec(0)


def _brute_shap(t, bins_row, num_features):
    """Exact Shapley values by subset enumeration (2^F)."""
    from itertools import combinations
    from math import factorial

    phi = np.zeros(num_features + 1)
    feats = list(range(num_features))
    F = num_features
    for i in feats:
        others = [f for f in feats if f != i]
        for k in range(F):
            for S in combinations(others, k):
                wgt = (factorial(k) * factorial(F - k - 1)) / factorial(F)
                gain = (_tree_cond_expect(t, bins_row, set(S) | {i})
                        - _tree_cond_expect(t, bins_row, set(S)))
                phi[i] += wgt * gain
    phi[F] = _tree_cond_expect(t, bins_row, set())
    return phi


def _tree_np(booster, idx=0):
    from lightgbm_tpu.models.tree import Tree

    t = booster.trees[idx]
    return {f: (None if getattr(t, f) is None else np.asarray(getattr(t, f)))
            for f in Tree._fields}


@pytest.fixture(scope="module")
def shap_model():
    rng = np.random.default_rng(5)
    n, F = 2000, 4
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (2 * X[:, 0] - X[:, 1] + X[:, 2] * (X[:, 3] > 0)
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 15}, ds, num_boost_round=12)
    return X, y, ds, b


def test_matches_bruteforce_single_tree(shap_model):
    X, y, ds, b = shap_model
    t = _tree_np(b, 0)
    codes = ds.bin_mapper.transform(X[:16])
    contrib = b.predict(X[:16], pred_contrib=True, num_iteration=1)
    lr = b.params.learning_rate
    init = float(np.float32(b.init_score_))
    for r in range(16):
        want = _brute_shap(t, codes[r], X.shape[1])
        got = contrib[r].astype(np.float64)
        # tree contributions scale by lr; bias additionally carries init
        np.testing.assert_allclose(got[:-1], lr * want[:-1],
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(got[-1], lr * want[-1] + init,
                                   rtol=1e-4, atol=1e-5)


def test_checksum_full_forest(shap_model):
    X, y, ds, b = shap_model
    contrib = b.predict(X[:200], pred_contrib=True)
    raw = b.predict(X[:200], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-4, atol=1e-4)


def test_truncation_and_shape(shap_model):
    X, y, ds, b = shap_model
    c5 = b.predict(X[:50], pred_contrib=True, num_iteration=5)
    raw5 = b.predict(X[:50], raw_score=True, num_iteration=5)
    assert c5.shape == (50, X.shape[1] + 1)
    np.testing.assert_allclose(c5.sum(axis=1), raw5, rtol=1e-4, atol=1e-4)


def test_binary_objective_raw_space():
    rng = np.random.default_rng(9)
    n, F = 1500, 4
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = (X[:, 0] + 0.5 * X[:, 1] + 0.3 * rng.normal(size=n)
         > 0).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "binary", "verbosity": -1}, ds,
                  num_boost_round=15)
    contrib = b.predict(X[:100], pred_contrib=True)
    raw = b.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-4, atol=1e-4)


def test_multiclass_contrib_shape():
    rng = np.random.default_rng(2)
    n, F, K = 900, 4, 3
    X = rng.normal(size=(n, F)).astype(np.float32)
    y = rng.integers(0, K, n).astype(np.float32)
    ds = lgb.Dataset(X, label=y)
    b = lgb.train({"objective": "multiclass", "num_class": K,
                   "verbosity": -1}, ds, num_boost_round=5)
    contrib = b.predict(X[:40], pred_contrib=True)
    assert contrib.shape == (40, K * (F + 1))
    raw = b.predict(X[:40], raw_score=True)           # [n, K]
    sums = contrib.reshape(40, K, F + 1).sum(axis=2)
    np.testing.assert_allclose(sums, raw, rtol=1e-4, atol=1e-4)


def test_categorical_split_contrib():
    rng = np.random.default_rng(4)
    n = 2000
    cat = rng.integers(0, 6, n)
    x1 = rng.normal(size=n)
    X = np.column_stack([cat, x1]).astype(np.float32)
    effect = np.asarray([2.0, -1.0, 0.5, 3.0, -2.0, 0.0])
    y = (effect[cat] + 0.2 * x1 + 0.1 * rng.normal(size=n)).astype(np.float32)
    ds = lgb.Dataset(X, label=y, categorical_feature=[0])
    b = lgb.train({"objective": "regression", "verbosity": -1,
                   "num_leaves": 15}, ds, num_boost_round=10)
    contrib = b.predict(X[:100], pred_contrib=True)
    raw = b.predict(X[:100], raw_score=True)
    np.testing.assert_allclose(contrib.sum(axis=1), raw,
                               rtol=1e-4, atol=1e-4)
    # the categorical feature drives the target -> dominant attribution
    assert np.abs(contrib[:, 0]).mean() > np.abs(contrib[:, 1]).mean()


def test_sklearn_wrapper_pred_contrib(shap_model):
    X, y, ds, b = shap_model
    reg = lgb.LGBMRegressor(n_estimators=8, verbosity=-1).fit(X, y)
    c = reg.predict(X[:20], pred_contrib=True)
    assert c.shape == (20, X.shape[1] + 1)

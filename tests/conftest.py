"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip sharding is validated on a virtual host-device mesh because only
one physical TPU chip is guaranteed (SURVEY.md §4 "test the psum path with
multi-device simulation").
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin's sitecustomize force-updates jax_platforms to
# "axon,cpu" at interpreter start, ignoring the env var — override it back
# before any backend initializes so tests really run on the 8-device CPU.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def small_regression(rng):
    """Tiny deterministic regression task usable on CPU."""
    n, f = 2000, 5
    X = rng.normal(0, 1, (n, f))
    y = (2.0 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rng.normal(0, 1, n))
    return X, y


@pytest.fixture(scope="session")
def small_binary(rng):
    n, f = 2000, 5
    X = rng.normal(0, 1, (n, f))
    logits = 1.5 * X[:, 0] - X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return X, y

"""Test env: force an 8-device virtual CPU platform BEFORE jax initializes.

Multi-chip sharding is validated on a virtual host-device mesh because only
one physical TPU chip is guaranteed (SURVEY.md §4 "test the psum path with
multi-device simulation").
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()

# The axon TPU plugin's sitecustomize force-updates jax_platforms to
# "axon,cpu" at interpreter start, ignoring the env var — override it back
# before any backend initializes so tests really run on the 8-device CPU.
import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest


@pytest.fixture(scope="session")
def rng():
    return np.random.default_rng(42)


@pytest.fixture(scope="session")
def small_regression(rng):
    """Tiny deterministic regression task usable on CPU."""
    n, f = 2000, 5
    X = rng.normal(0, 1, (n, f))
    y = (2.0 * X[:, 0] + np.sin(3 * X[:, 1]) + 0.5 * X[:, 2] * X[:, 3]
         + 0.1 * rng.normal(0, 1, n))
    return X, y


@pytest.fixture(scope="session")
def small_binary(rng):
    n, f = 2000, 5
    X = rng.normal(0, 1, (n, f))
    logits = 1.5 * X[:, 0] - X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    return X, y


# ---------------------------------------------------------------------------
# XLA-CPU compile-state hygiene: with ~160 tests compiling hundreds of large
# programs (8-device shard_maps, scan-of-scan SHAP/fused programs) in ONE
# process, the CPU backend's compiler eventually segfaults inside
# backend_compile (observed roaming across unrelated tests past ~50% of the
# suite; stack-limit independent).  Dropping every cached executable and the
# framework's jit-wrapper caches every N tests keeps the per-process compile
# state bounded.  Cost: a few recompiles per block; correctness unaffected.
# ---------------------------------------------------------------------------
_TESTS_PER_CACHE_EPOCH = 24
_test_counter = [0]


def _clear_all_jit_caches():
    import jax

    from lightgbm_tpu.models import gbdt as _g

    for fn_name in ("_round_fn", "_multi_round_fn", "_tree_pred_fn",
                    "_linear_tree_pred_fn", "_eval_fn", "_bag_fn",
                    "_feature_mask_fn"):
        fn = getattr(_g, fn_name, None)
        if fn is not None and hasattr(fn, "cache_clear"):
            fn.cache_clear()
    try:
        from lightgbm_tpu.models import fused as _f
        _f._fused_cv_fn.cache_clear()
    except Exception:
        pass
    try:
        from lightgbm_tpu.parallel import data_parallel as _dp
        _dp.make_dp_train_step.cache_clear()
        _dp.make_dp_grow_step.cache_clear()
    except Exception:
        pass
    try:
        from lightgbm_tpu.parallel import feature_parallel as _fp
        _fp.make_fp_train_step.cache_clear()
    except Exception:
        pass
    try:
        from lightgbm_tpu.ops import shap as _s
        _s._forest_shap_fn.cache_clear()
    except Exception:
        pass
    try:
        from lightgbm_tpu.ops import histogram as _h
        for name in dir(_h):
            f = getattr(_h, name)
            if hasattr(f, "cache_clear"):
                f.cache_clear()
    except Exception:
        pass
    jax.clear_caches()


@pytest.fixture(autouse=True)
def _bounded_compile_state():
    yield
    _test_counter[0] += 1
    if _test_counter[0] % _TESTS_PER_CACHE_EPOCH == 0:
        _clear_all_jit_caches()


# ---------------------------------------------------------------------------
# Fast/slow test lanes (VERDICT r2 item 8: the full suite outgrew a judge
# session — 26 min at 179 tests on this 1-core box, jax-CPU compiles
# dominating).  The default profile (pytest.ini: -m "not slow") runs the
# functional surface; the heavyweight quality/mesh/e2e tests (>~13 s each,
# ~60% of total wall) carry the `slow` marker and run via
# `python -m pytest tests/ -m slow` (or `-m ""` for everything).
# Names listed here instead of per-file marks so the lane assignment lives
# in ONE reviewable place next to the measured durations that justify it.
# ---------------------------------------------------------------------------
_SLOW_TESTS = {
    "test_fp_categorical_matches_serial",
    "test_fp_multiclass_matches_serial",
    "test_bagging_and_feature_fraction_run",
    "test_beats_linear_model",
    "test_binary_objective_auc",
    "test_bundled_training_matches_unbundled_quality",
    "test_categorical_split_contrib",
    "test_cli_module_invocation",
    "test_close_to_sklearn_hist_gbdt",
    "test_dart_multiclass",
    "test_dart_quality_comparable_to_gbdt",
    "test_dart_trains_and_fits",
    "test_dart_with_valid_set_early_stopping",
    "test_dp_lambdarank_matches_serial",
    "test_dp_multiclass_matches_serial",
    "test_dryrun_multichip_entrypoint",
    "test_extra_trees_learns_and_differs",
    "test_frontier_grower_supports_categoricals",
    "test_frontier_policy_end_to_end_quality",
    "test_fused_cv_batch_multiple_configs",
    "test_fused_cv_categorical_matches_host_loop",
    "test_fused_cv_close_to_host_cv",
    "test_gamma_objective",
    "test_interaction_constraints_respected",
    "test_l1_leaf_renewal_beats_plain_surrogate",
    "test_lambdarank_beats_pointwise",
    "test_lambdarank_cv_group_aware",
    "test_mape_objective",
    "test_max_delta_step_caps_leaf_values",
    "test_monotone_constraints_frontier_and_strict",
    "test_monotone_constraints_hold",
    "test_monotone_string_form_and_validation",
    "test_monotone_unconstrained_model_violates",
    "test_monotone_with_goss_and_dp_mesh",
    "test_quantile_init_score_and_renewal",
    "test_subset_splits_beat_threshold_splits",
    "test_train_api_tree_learner_data_matches_serial",
    "test_train_api_tree_learner_data_with_bagging",
    "test_train_api_tree_learner_data_with_categorical",
    "test_train_api_tree_learner_data_with_goss",
    "test_train_api_tree_learner_feature_matches_serial",
    "test_tweedie_objective",
    # second tier (8-13 s each on the 1-core box; fast lane was 9:24
    # without them, ~5:50 with — measured 2026-07-31)
    "test_cross_entropy_continuous_labels",
    "test_fused_goss_matches_host_loop",
    "test_frontier_deterministic",
    "test_fused_cv_early_stops",
    "test_training_loss_decreases",
    "test_deterministic_same_seed",
    "test_early_stopping_with_valid_set",
    "test_bundled_predict_consistency_and_importance",
    "test_linear_beats_constant_on_piecewise_linear",
    "test_wave1_matches_strict_structure",
    "test_map_eval_and_early_stopping",
    "test_reset_parameter_callback",
    "test_max_depth_limits_growth",
    "test_init_model_continuation_matches_single_run",
    "test_cv_with_categoricals_runs",
    "test_chunked_fit_matches_single_pass",
    "test_dart_deterministic_under_seed",
    "test_multiclass_random_forest",
    "test_init_model_from_file_and_different_lr",
    "test_multiclass_contrib_shape",
    "test_dp_multiclass_goss_trains",
    "test_staged_prediction_prefix_consistency",
    # third tier (r20: the fast lane crept to 99.6% of the 870 s verify
    # budget — 866.61 s measured 2026-08-08 — so the heaviest parity
    # tests move here; check.sh's tier2-heavy lane still runs every one
    # of them by node id on each CI pass)
    "test_fp_wave_growth_matches_serial",            # 27.0 s
    "test_mesh_shape_routing",                       # 19.8 s
    "test_daemon_retunes_every_n_flips",             # 15.8 s
    "test_fused_cv_multiclass_matches_host_loop",    # 15.1 s
    "test_histogram_wire_override_param",            # 14.7 s
    "test_screened_in_memory_matches_streamed",      # 10.5 s (both params)
    "test_screened_stream_moves_fewer_bytes",        #  4.6 s
}


def pytest_collection_modifyitems(config, items):
    for item in items:
        if item.name.split("[")[0] in _SLOW_TESTS:
            item.add_marker(pytest.mark.slow)

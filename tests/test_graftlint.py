"""graftlint's own test suite (r8 tentpole).

Three layers of coverage:

* seeded violations — one minimal snippet per rule ID, asserting the
  rule fires at exactly the expected line (and nowhere else), plus
  negative twins asserting the clean spelling stays silent;
* the baseline machinery — TOML-subset parsing, count-based
  suppression, stale-entry reporting, format errors;
* the gates themselves — the package tree lints clean through the real
  CLI, the VMEM estimates fit the 16 MB scope, and the zero-recompile
  guarantees hold (serving bucket ladder, fused train step).
"""

import pytest

from lightgbm_tpu.analysis.baseline import (BaselineError, apply_baseline,
                                            parse_baseline)
from lightgbm_tpu.analysis.cli import main as lint_main
from lightgbm_tpu.analysis.engine import run_lint
from lightgbm_tpu.analysis.rules import analyze_source


def findings(src, path="fix.py"):
    return analyze_source(path, src)


def rules_at(src, rule):
    """Sorted line numbers where ``rule`` fires."""
    return [f.line for f in findings(src) if f.rule == rule]


def line_of(src, needle):
    for i, text in enumerate(src.splitlines(), 1):
        if needle in text:
            return i
    raise AssertionError(f"{needle!r} not in fixture")


# ---------------------------------------------------------------------------
# seeded violations, one per rule
# ---------------------------------------------------------------------------

GL001_BAD = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    if jnp.sum(x) > 0:
        return x
    return -x
"""

GL001_GOOD = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return jnp.where(jnp.sum(x) > 0, x, -x)
"""


def test_gl001_traced_branch():
    assert rules_at(GL001_BAD, "GL001") == [line_of(GL001_BAD, "if ")]
    assert rules_at(GL001_GOOD, "GL001") == []


def test_gl001_host_constant_backend_is_clean():
    src = GL001_BAD.replace("jnp.sum(x) > 0",
                            'jax.default_backend() == "tpu"')
    assert rules_at(src, "GL001") == []


GL002_BAD = """\
import jax
import numpy as np

@jax.jit
def f(x):
    y = x * 2
    return y.item()

def g(x):
    return jax.lax.scan(lambda c, v: (c + float(x), v), 0.0, x)
"""


def test_gl002_host_sync():
    lines = rules_at(GL002_BAD, "GL002")
    assert line_of(GL002_BAD, ".item()") in lines


def test_gl002_np_asarray_on_traced_param():
    src = ("import jax\nimport numpy as np\n\n@jax.jit\n"
           "def f(x):\n    return np.asarray(x)\n")
    assert rules_at(src, "GL002") == [6]
    # np.asarray of plain host data in untraced code is fine
    clean = "import numpy as np\n\ndef g(rows):\n    return np.asarray(rows)\n"
    assert rules_at(clean, "GL002") == []


def test_gl002_block_until_ready_fires_anywhere():
    src = ("import jax\n\ndef warm(fn, x):\n"
           "    jax.block_until_ready(fn(x))\n")
    assert rules_at(src, "GL002") == [4]


def test_gl002_np_asarray_over_device_expression():
    src = ("import numpy as np\nimport jax.numpy as jnp\n\n"
           "def dispatch(fn, codes):\n"
           "    return np.asarray(fn(jnp.asarray(codes)))\n")
    assert rules_at(src, "GL002") == [5]


GL003_BAD = """\
import jax
import jax.numpy as jnp
import functools
import jax.experimental.pallas as pl

def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...].astype(jnp.float64)

jax.config.update("jax_enable_x64", True)
"""


def test_gl003_float64_traps():
    lines = rules_at(GL003_BAD, "GL003")
    assert line_of(GL003_BAD, "jnp.float64") in lines
    assert line_of(GL003_BAD, "jax_enable_x64") in lines


def test_gl003_silent_in_host_only_module():
    # np.float64 in a module with no kernels is host-side bookkeeping
    src = "import numpy as np\n\nout = np.zeros(3, dtype=np.float64)\n"
    assert rules_at(src, "GL003") == []


GL004_BAD = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("num_leaves",))
def f(x, n):
    return x

@jax.jit
def g(x, depth):
    acc = x
    for _ in range(depth):
        acc = acc + 1
    return acc
"""


def test_gl004_static_argnames():
    lines = rules_at(GL004_BAD, "GL004")
    assert line_of(GL004_BAD, "static_argnames") in lines   # no such param
    assert line_of(GL004_BAD, "range(depth)") in lines      # needs static
    # naming a real param + marking the loop bound static is clean
    good = GL004_BAD.replace('("num_leaves",)', '("n",)').replace(
        "def g(x, depth):",
        "def g(x, depth):  # graftlint: GL004").replace(
        "    for _ in range(depth):",
        "    for _ in range(3):")
    assert rules_at(good, "GL004") == []


GL005_BAD = """\
import jax.numpy as jnp
import numpy as np

def f(n):
    x = jnp.zeros(n)
    x[0] = 1.0
    y = np.zeros(n)
    y[0] = 1.0
    return x, y
"""


def test_gl005_inplace_mutation():
    # the jax array assignment fires; the numpy one is legitimate
    assert rules_at(GL005_BAD, "GL005") == [line_of(GL005_BAD, "x[0]")]


GL006_BAD = """\
import jax

def run(step, params, batch):
    fast = jax.jit(step, donate_argnums=(0,))
    out = fast(params, batch)
    return out, params.sum()
"""


def test_gl006_donated_reuse():
    assert rules_at(GL006_BAD, "GL006") == [
        line_of(GL006_BAD, "params.sum()")]
    good = GL006_BAD.replace("out, params.sum()", "out, out.sum()")
    assert rules_at(good, "GL006") == []


GL007_BAD = """\
import jax.numpy as jnp
from jax.experimental import pallas as pl

def matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = jnp.dot(a_ref[...], b_ref[...])
"""


def test_gl007_kernel_dot_dtype():
    assert rules_at(GL007_BAD, "GL007") == [line_of(GL007_BAD, "jnp.dot")]
    good = GL007_BAD.replace(
        "jnp.dot(a_ref[...], b_ref[...])",
        "jnp.dot(a_ref[...], b_ref[...], "
        "preferred_element_type=jnp.float32)")
    assert rules_at(good, "GL007") == []
    # the same dot OUTSIDE kernel code is fine (XLA picks f32 there)
    host = ("import jax.numpy as jnp\n\ndef f(a, b):\n"
            "    return jnp.dot(a, b)\n")
    assert rules_at(host, "GL007") == []


def test_gl000_syntax_error():
    fs = findings("def f(:\n")
    assert [f.rule for f in fs] == ["GL000"]


def test_inline_waiver():
    src = GL007_BAD.replace(
        "jnp.dot(a_ref[...], b_ref[...])",
        "jnp.dot(a_ref[...], b_ref[...])  # graftlint: GL007 — bf16 ok")
    assert rules_at(src, "GL007") == []


def test_tracing_closure_through_local_calls():
    # helper() is traced only because a jitted function calls it
    src = """\
import jax

def helper(x):
    return x.item()

@jax.jit
def entry(x):
    return helper(x)
"""
    assert rules_at(src, "GL002") == [line_of(src, ".item()")]


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------

def test_baseline_parse_and_suppress():
    sup = parse_baseline("""
# ledger
[[suppress]]
rule = "GL002"
path = "pkg/mod.py"
count = 2
reason = "api boundary"
""")
    assert len(sup) == 1 and sup[0].count == 2
    fs = findings(GL002_BAD, path="pkg/mod.py")
    gl2 = [f for f in fs if f.rule == "GL002"]
    res = apply_baseline(gl2[:1], sup)
    assert not res.unsuppressed and len(res.suppressed) == 1
    assert res.stale and res.stale[0].used == 1   # count=2, one used


def test_baseline_count_exhaustion():
    sup = parse_baseline('[[suppress]]\nrule = "GL002"\n'
                         'path = "p.py"\ncount = 1\nreason = "x"\n')
    fs = findings(GL002_BAD, path="p.py")
    gl2 = [f for f in fs if f.rule == "GL002"]
    assert len(gl2) >= 1
    res = apply_baseline(gl2 + gl2, sup)          # two findings, count=1
    assert len(res.suppressed) == 1
    assert len(res.unsuppressed) == len(gl2) * 2 - 1


@pytest.mark.parametrize("bad", [
    "[[other]]\nrule = \"GL001\"\n",              # wrong table name
    "[suppress]\n",                                # not an array table
    "rule = \"GL001\"\n",                          # key outside table
    "[[suppress]]\nrule = \"GL001\"\npath = \"p\"\nreason = \"\"\n",
    "[[suppress]]\nrule = \"GL001\"\npath = \"p\"\ncount = 0\n"
    "reason = \"r\"\n",
    "[[suppress]]\npath = \"p\"\nreason = \"r\"\n",   # missing rule
])
def test_baseline_format_errors(bad):
    with pytest.raises(BaselineError):
        parse_baseline(bad)


# ---------------------------------------------------------------------------
# the gates themselves
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_package_tree_lints_clean():
    report = run_lint()
    assert report.ok, "\n".join(f.format() for f in report.unsuppressed)
    assert not report.stale, [s.reason for s in report.stale]
    assert report.files_checked > 30


@pytest.mark.lint
def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "seeded.py"
    bad.write_text(GL001_BAD)
    assert lint_main([str(bad), "--no-vmem", "-q"]) == 1
    out = capsys.readouterr().out
    assert "GL001" in out and "seeded.py:6" in out
    good = tmp_path / "clean.py"
    good.write_text(GL001_GOOD)
    assert lint_main([str(good), "--no-vmem", "-q"]) == 0


@pytest.mark.lint
@pytest.mark.parametrize("snippet,rule", [
    (GL001_BAD, "GL001"), (GL002_BAD, "GL002"), (GL003_BAD, "GL003"),
    (GL004_BAD, "GL004"), (GL005_BAD, "GL005"), (GL006_BAD, "GL006"),
    (GL007_BAD, "GL007"),
], ids=["GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007"])
def test_cli_nonzero_per_seeded_rule(tmp_path, snippet, rule, capsys):
    p = tmp_path / f"{rule.lower()}.py"
    p.write_text(snippet)
    assert lint_main([str(p), "--no-vmem", "-q"]) == 1
    assert rule in capsys.readouterr().out


def test_vmem_specs_fit_budget():
    from lightgbm_tpu.analysis.vmem import check_vmem_specs

    for r in check_vmem_specs():
        assert r["ok"], r
        assert r["estimated_mb"] > 0.1, r      # the model isn't vacuous


@pytest.mark.lint
def test_serving_recompile_sweep():
    from lightgbm_tpu.analysis.budgets import serving_recompile_sweep

    r = serving_recompile_sweep(max_bucket=64)
    assert r["ok"], r
    assert r["compiles"] <= 7 and r["recompiles_on_repeat"] == 0


@pytest.mark.lint
def test_fused_train_step_single_compile():
    from lightgbm_tpu.analysis.budgets import fused_train_step_recompiles

    r = fused_train_step_recompiles(n_hyper_batches=3)
    assert r["ok"], r
    assert r["compiles"] <= 1

"""graftlint's own test suite (r8 tentpole).

Three layers of coverage:

* seeded violations — one minimal snippet per rule ID, asserting the
  rule fires at exactly the expected line (and nowhere else), plus
  negative twins asserting the clean spelling stays silent;
* the baseline machinery — TOML-subset parsing, count-based
  suppression, stale-entry reporting, format errors;
* the gates themselves — the package tree lints clean through the real
  CLI, the VMEM estimates fit the 16 MB scope, and the zero-recompile
  guarantees hold (serving bucket ladder, fused train step).
"""

import os

import pytest

from lightgbm_tpu.analysis.baseline import (BaselineError, apply_baseline,
                                            parse_baseline)
from lightgbm_tpu.analysis.cli import main as lint_main
from lightgbm_tpu.analysis.engine import run_lint
from lightgbm_tpu.analysis.program import Program, fault_site_findings
from lightgbm_tpu.analysis.rules import analyze_source


def findings(src, path="fix.py"):
    return analyze_source(path, src)


def rules_at(src, rule):
    """Sorted line numbers where ``rule`` fires."""
    return [f.line for f in findings(src) if f.rule == rule]


def line_of(src, needle):
    for i, text in enumerate(src.splitlines(), 1):
        if needle in text:
            return i
    raise AssertionError(f"{needle!r} not in fixture")


# ---------------------------------------------------------------------------
# seeded violations, one per rule
# ---------------------------------------------------------------------------

GL001_BAD = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    if jnp.sum(x) > 0:
        return x
    return -x
"""

GL001_GOOD = """\
import jax
import jax.numpy as jnp

@jax.jit
def f(x):
    return jnp.where(jnp.sum(x) > 0, x, -x)
"""


def test_gl001_traced_branch():
    assert rules_at(GL001_BAD, "GL001") == [line_of(GL001_BAD, "if ")]
    assert rules_at(GL001_GOOD, "GL001") == []


def test_gl001_host_constant_backend_is_clean():
    src = GL001_BAD.replace("jnp.sum(x) > 0",
                            'jax.default_backend() == "tpu"')
    assert rules_at(src, "GL001") == []


GL002_BAD = """\
import jax
import numpy as np

@jax.jit
def f(x):
    y = x * 2
    return y.item()

def g(x):
    return jax.lax.scan(lambda c, v: (c + float(x), v), 0.0, x)
"""


def test_gl002_host_sync():
    lines = rules_at(GL002_BAD, "GL002")
    assert line_of(GL002_BAD, ".item()") in lines


def test_gl002_np_asarray_on_traced_param():
    src = ("import jax\nimport numpy as np\n\n@jax.jit\n"
           "def f(x):\n    return np.asarray(x)\n")
    assert rules_at(src, "GL002") == [6]
    # np.asarray of plain host data in untraced code is fine
    clean = "import numpy as np\n\ndef g(rows):\n    return np.asarray(rows)\n"
    assert rules_at(clean, "GL002") == []


def test_gl002_block_until_ready_fires_anywhere():
    src = ("import jax\n\ndef warm(fn, x):\n"
           "    jax.block_until_ready(fn(x))\n")
    assert rules_at(src, "GL002") == [4]


def test_gl002_np_asarray_over_device_expression():
    src = ("import numpy as np\nimport jax.numpy as jnp\n\n"
           "def dispatch(fn, codes):\n"
           "    return np.asarray(fn(jnp.asarray(codes)))\n")
    assert rules_at(src, "GL002") == [5]


GL003_BAD = """\
import jax
import jax.numpy as jnp
import functools
import jax.experimental.pallas as pl

def kern(x_ref, o_ref):
    o_ref[...] = x_ref[...].astype(jnp.float64)

jax.config.update("jax_enable_x64", True)
"""


def test_gl003_float64_traps():
    lines = rules_at(GL003_BAD, "GL003")
    assert line_of(GL003_BAD, "jnp.float64") in lines
    assert line_of(GL003_BAD, "jax_enable_x64") in lines


def test_gl003_silent_in_host_only_module():
    # np.float64 in a module with no kernels is host-side bookkeeping
    src = "import numpy as np\n\nout = np.zeros(3, dtype=np.float64)\n"
    assert rules_at(src, "GL003") == []


GL004_BAD = """\
import jax
from functools import partial

@partial(jax.jit, static_argnames=("num_leaves",))
def f(x, n):
    return x

@jax.jit
def g(x, depth):
    acc = x
    for _ in range(depth):
        acc = acc + 1
    return acc
"""


def test_gl004_static_argnames():
    lines = rules_at(GL004_BAD, "GL004")
    assert line_of(GL004_BAD, "static_argnames") in lines   # no such param
    assert line_of(GL004_BAD, "range(depth)") in lines      # needs static
    # naming a real param + marking the loop bound static is clean
    good = GL004_BAD.replace('("num_leaves",)', '("n",)').replace(
        "def g(x, depth):",
        "def g(x, depth):  # graftlint: GL004").replace(
        "    for _ in range(depth):",
        "    for _ in range(3):")
    assert rules_at(good, "GL004") == []


GL005_BAD = """\
import jax.numpy as jnp
import numpy as np

def f(n):
    x = jnp.zeros(n)
    x[0] = 1.0
    y = np.zeros(n)
    y[0] = 1.0
    return x, y
"""


def test_gl005_inplace_mutation():
    # the jax array assignment fires; the numpy one is legitimate
    assert rules_at(GL005_BAD, "GL005") == [line_of(GL005_BAD, "x[0]")]


GL006_BAD = """\
import jax

def run(step, params, batch):
    fast = jax.jit(step, donate_argnums=(0,))
    out = fast(params, batch)
    return out, params.sum()
"""


def test_gl006_donated_reuse():
    assert rules_at(GL006_BAD, "GL006") == [
        line_of(GL006_BAD, "params.sum()")]
    good = GL006_BAD.replace("out, params.sum()", "out, out.sum()")
    assert rules_at(good, "GL006") == []


GL007_BAD = """\
import jax.numpy as jnp
from jax.experimental import pallas as pl

def matmul_kernel(a_ref, b_ref, o_ref):
    o_ref[...] = jnp.dot(a_ref[...], b_ref[...])
"""


def test_gl007_kernel_dot_dtype():
    assert rules_at(GL007_BAD, "GL007") == [line_of(GL007_BAD, "jnp.dot")]
    good = GL007_BAD.replace(
        "jnp.dot(a_ref[...], b_ref[...])",
        "jnp.dot(a_ref[...], b_ref[...], "
        "preferred_element_type=jnp.float32)")
    assert rules_at(good, "GL007") == []
    # the same dot OUTSIDE kernel code is fine (XLA picks f32 there)
    host = ("import jax.numpy as jnp\n\ndef f(a, b):\n"
            "    return jnp.dot(a, b)\n")
    assert rules_at(host, "GL007") == []


def test_gl000_syntax_error():
    fs = findings("def f(:\n")
    assert [f.rule for f in fs] == ["GL000"]


def test_inline_waiver():
    src = GL007_BAD.replace(
        "jnp.dot(a_ref[...], b_ref[...])",
        "jnp.dot(a_ref[...], b_ref[...])  # graftlint: GL007 — bf16 ok")
    assert rules_at(src, "GL007") == []


def test_tracing_closure_through_local_calls():
    # helper() is traced only because a jitted function calls it
    src = """\
import jax

def helper(x):
    return x.item()

@jax.jit
def entry(x):
    return helper(x)
"""
    assert rules_at(src, "GL002") == [line_of(src, ".item()")]


# ---------------------------------------------------------------------------
# r16: GL008-GL011, one seeded violation + negative twin per rule
# ---------------------------------------------------------------------------

GL008_BAD = """\
import time
import random
import numpy as np
from datetime import datetime

def tick():
    t0 = time.perf_counter()
    time.sleep(0.1)
    stamp = datetime.now()
    jitter = random.random()
    rng = np.random.default_rng()
    legacy = np.random.rand(3)
    return t0, stamp, jitter, rng, legacy
"""

GL008_GOOD = """\
import time
import numpy as np

def tick(clock=time.monotonic, rng=None):
    rng = np.random.default_rng(1234) if rng is None else rng
    return clock(), rng.uniform()
"""


def test_gl008_direct_wall_clock_and_global_rng():
    lines = rules_at(GL008_BAD, "GL008")
    assert lines == [line_of(GL008_BAD, "perf_counter"),
                     line_of(GL008_BAD, "time.sleep"),
                     line_of(GL008_BAD, "datetime.now"),
                     line_of(GL008_BAD, "random.random"),
                     line_of(GL008_BAD, "default_rng()"),
                     line_of(GL008_BAD, "np.random.rand")]


def test_gl008_injected_clock_and_seeded_rng_are_clean():
    # `clock=time.monotonic` is a bare REFERENCE (the sanctioned
    # injection idiom), `clock()` resolves to a parameter, and the
    # default_rng has an explicit seed — nothing fires
    assert rules_at(GL008_GOOD, "GL008") == []


def test_gl008_from_import_form():
    src = ("from time import perf_counter\n\n"
           "def t():\n    return perf_counter()\n")
    assert rules_at(src, "GL008") == [4]


def test_gl008_inline_waiver():
    src = GL008_BAD.replace(
        "time.sleep(0.1)",
        "time.sleep(0.1)  # graftlint: GL008 — operator backoff")
    assert line_of(src, "time.sleep") not in rules_at(src, "GL008")


GL009_BAD = """\
import threading

class Stats:
    def __init__(self):
        self._lock = threading.Lock()
        self.hits = 0
        self.events = []

    def bump(self):
        with self._lock:
            self.hits += 1
            self.events.append("hit")

    def racy_reset(self):
        self.hits *= 0
        self.events.clear()
"""

GL009_GOOD = GL009_BAD.replace(
    "threading.Lock()", "threading.RLock()").replace(
    """    def racy_reset(self):
        self.hits *= 0
        self.events.clear()""",
    """    def racy_reset(self):
        with self._lock:
            self.hits *= 0
            self.events.clear()""")


def test_gl009_mixed_locked_unlocked_mutation():
    # both attrs are written under the lock in bump() and without it in
    # racy_reset() -> the rule flags the UNLOCKED sites
    assert rules_at(GL009_BAD, "GL009") == [
        line_of(GL009_BAD, "self.hits *= 0"),
        line_of(GL009_BAD, "self.events.clear()")]


def test_gl009_lock_correct_twin_is_silent():
    assert rules_at(GL009_GOOD, "GL009") == []


def test_gl009_init_and_lockless_classes_exempt():
    # __init__ writes precede sharing and never count as unlocked; a
    # class with no lock attribute is out of scope entirely
    lockless = GL009_BAD.replace(
        "        self._lock = threading.Lock()\n", "").replace(
        "        with self._lock:\n            self.hits += 1\n"
        "            self.events.append(\"hit\")",
        "        self.hits += 1\n        self.events.append(\"hit\")")
    assert rules_at(lockless, "GL009") == []


GL011_BAD = """\
def load(path):
    try:
        return open(path).read()
    except:
        return None

def push(x):
    try:
        x.send()
    except ValueError:
        pass

def fail():
    raise Exception("boom")
"""

GL011_GOOD = """\
class PushError(RuntimeError):
    pass

def load(path):
    try:
        return open(path).read()
    except OSError:
        return None

def push(x, log):
    try:
        x.send()
    except ValueError as e:
        log.append(e)

def fail():
    raise PushError("boom")
"""


def test_gl011_bare_swallowed_and_untyped():
    assert rules_at(GL011_BAD, "GL011") == [
        line_of(GL011_BAD, "except:"),
        line_of(GL011_BAD, "except ValueError"),
        line_of(GL011_BAD, "raise Exception")]


def test_gl011_typed_twin_is_silent():
    assert rules_at(GL011_GOOD, "GL011") == []


def test_gl011_inline_waiver_on_except_line():
    src = GL011_BAD.replace(
        "    except ValueError:",
        "    except ValueError:  # graftlint: GL011 — best-effort push")
    assert line_of(src, "except ValueError") not in rules_at(src, "GL011")


# ---------------------------------------------------------------------------
# r16 tentpole: whole-program analysis (cross-module closure, GL010)
# ---------------------------------------------------------------------------

def prog_findings(modules, rule=None):
    fs = Program(modules).run_rules()
    return [f for f in fs if rule is None or f.rule == rule]


def test_cross_module_traced_closure():
    # work() lives in another FILE and is traced only because a jitted
    # entry point imports and calls it — per-file analysis cannot see
    # this; the Program closure must
    entry = ("import jax\nfrom pkg.helper import work\n\n@jax.jit\n"
             "def run(x):\n    return work(x)\n")
    helper = "def work(x):\n    return x.item()\n"
    fs = prog_findings([("pkg/entry.py", entry),
                        ("pkg/helper.py", helper)], "GL002")
    assert [(f.path, f.line) for f in fs] == [("pkg/helper.py", 2)]
    # the same helper with no traced caller stays clean
    assert prog_findings([("pkg/helper.py", helper)], "GL002") == []


def test_cross_module_closure_through_module_alias():
    # dotted call form: entry imports the MODULE and calls pkg.work(...)
    entry = ("import jax\nfrom pkg import helper\n\n@jax.jit\n"
             "def run(x):\n    return helper.work(x)\n")
    helper = "def work(x):\n    return x.item()\n"
    fs = prog_findings([("pkg/entry.py", entry),
                        ("pkg/helper.py", helper)], "GL002")
    assert [(f.path, f.line) for f in fs] == [("pkg/helper.py", 2)]


def test_cross_module_closure_relative_import():
    entry = ("import jax\nfrom .helper import work\n\n@jax.jit\n"
             "def run(x):\n    return work(x)\n")
    helper = "def work(x):\n    return x.item()\n"
    fs = prog_findings([("pkg/entry.py", entry),
                        ("pkg/helper.py", helper)], "GL002")
    assert [(f.path, f.line) for f in fs] == [("pkg/helper.py", 2)]


_GL010_FAULTS = """\
SERVING_SITES = ("predict", "flip")
TRAINING_SITES = ()
PIPELINE_SITES = ()
SITES = SERVING_SITES + TRAINING_SITES + PIPELINE_SITES
"""

_GL010_USE = """\
class Runtime:
    def __init__(self, faults):
        self.faults = faults

    def predict(self):
        self.faults.check("predict")
        self.faults.check("mistyped")
"""


def test_gl010_all_three_drift_directions():
    prog = Program([("pkg/faults.py", _GL010_FAULTS),
                    ("pkg/runtime.py", _GL010_USE)])
    fs = fault_site_findings(prog, [("tests/test_x.py",
                                     "SITE = 'predict'\n")])
    msgs = {(f.path, f.message.split("'")[1]) for f in fs}
    # direction 1: consulted site missing from the registry
    assert ("pkg/runtime.py", "mistyped") in msgs
    # direction 2: registered site never consulted
    assert ("pkg/faults.py", "flip") in msgs
    # direction 3: registered site absent from the chaos tests
    assert sum(1 for p, s in msgs if s == "flip") == 1  # unused+untested
    untested = [f for f in fs if "not referenced by any" in f.message]
    assert {f.message.split("'")[1] for f in untested} == {"flip"}
    assert all(f.rule == "GL010" for f in fs)


def test_gl010_drift_free_twin_is_silent():
    use = _GL010_USE.replace('self.faults.check("mistyped")',
                             'self.faults.check("flip")')
    prog = Program([("pkg/faults.py", _GL010_FAULTS),
                    ("pkg/runtime.py", use)])
    tests = [("tests/test_x.py", "COVERED = ('predict', 'flip')\n")]
    assert fault_site_findings(prog, tests) == []


def test_gl010_arm_and_faultspec_count_as_consultation():
    use = ("from pkg.faults import FaultSpec\n\n"
           "def chaos(inj):\n"
           "    inj.arm('predict')\n"
           "    return FaultSpec(site='flip')\n")
    prog = Program([("pkg/faults.py", _GL010_FAULTS),
                    ("pkg/chaos.py", use)])
    fs = fault_site_findings(prog, ())     # no tests -> coverage skipped
    assert fs == []


def test_gl010_noninjectorish_check_is_ignored():
    # .check() on something that is not a fault injector must not count
    # as consultation (precision guard) — "predict"/"flip" stay unused
    use = "def f(validator):\n    validator.check('predict')\n"
    prog = Program([("pkg/faults.py", _GL010_FAULTS),
                    ("pkg/other.py", use)])
    fs = fault_site_findings(prog, ())
    assert {f.message.split("'")[1] for f in fs} == {"predict", "flip"}


@pytest.mark.lint
def test_real_registry_has_no_drift_and_pipeline_sites_covered():
    """The repo's own faults.SITES registry: every site consulted, every
    site chaos-tested — including all four r15 PIPELINE_SITES and
    all three r17 SWEEP_SITES."""
    from lightgbm_tpu import faults
    from lightgbm_tpu.analysis.engine import (PACKAGE_ROOT, REPO_ROOT,
                                              _read_sources)

    prog = Program(_read_sources([PACKAGE_ROOT]))
    tests = _read_sources([os.path.join(REPO_ROOT, "tests")])
    assert fault_site_findings(prog, tests) == []
    assert set(faults.PIPELINE_SITES) == {
        "data_arrival", "continue_train", "artifact_push", "flip"}
    assert set(faults.SWEEP_SITES) == {
        "sweep_segment", "sweep_record", "sweep_promote"}
    # and the drift check is not vacuous: drop the test tree and the
    # coverage direction must be able to fire
    assert len(faults.SITES) == 15


# ---------------------------------------------------------------------------
# r16: Layer-2 budget anchors (specs must reference live symbols)
# ---------------------------------------------------------------------------

def test_budget_anchors_all_live():
    from lightgbm_tpu.analysis.budgets import check_budget_anchors

    res = check_budget_anchors()
    assert len(res) >= 15
    assert all(r["ok"] for r in res), [r for r in res if not r["ok"]]


def test_budget_anchor_detects_renamed_symbol_and_dead_file():
    from lightgbm_tpu.analysis.budgets import check_budget_anchors

    res = check_budget_anchors({
        "launch": (("lightgbm_tpu/models/tree.py", "grow_tree"),
                   ("lightgbm_tpu/models/tree.py", "grow_tree_v2"),
                   ("lightgbm_tpu/models/gone.py", "grow_tree"))})
    by = {(r["path"], r["symbol"]): r for r in res}
    assert by[("lightgbm_tpu/models/tree.py", "grow_tree")]["ok"]
    stale = by[("lightgbm_tpu/models/tree.py", "grow_tree_v2")]
    assert not stale["ok"] and "grow_tree_v2" in stale["why"]
    assert not by[("lightgbm_tpu/models/gone.py", "grow_tree")]["ok"]


# ---------------------------------------------------------------------------
# baseline machinery
# ---------------------------------------------------------------------------

def test_baseline_parse_and_suppress():
    sup = parse_baseline("""
# ledger
[[suppress]]
rule = "GL002"
path = "pkg/mod.py"
count = 2
reason = "api boundary"
""")
    assert len(sup) == 1 and sup[0].count == 2
    fs = findings(GL002_BAD, path="pkg/mod.py")
    gl2 = [f for f in fs if f.rule == "GL002"]
    res = apply_baseline(gl2[:1], sup)
    assert not res.unsuppressed and len(res.suppressed) == 1
    assert res.stale and res.stale[0].used == 1   # count=2, one used


def test_baseline_count_exhaustion():
    sup = parse_baseline('[[suppress]]\nrule = "GL002"\n'
                         'path = "p.py"\ncount = 1\nreason = "x"\n')
    fs = findings(GL002_BAD, path="p.py")
    gl2 = [f for f in fs if f.rule == "GL002"]
    assert len(gl2) >= 1
    res = apply_baseline(gl2 + gl2, sup)          # two findings, count=1
    assert len(res.suppressed) == 1
    assert len(res.unsuppressed) == len(gl2) * 2 - 1


@pytest.mark.parametrize("bad", [
    "[[other]]\nrule = \"GL001\"\n",              # wrong table name
    "[suppress]\n",                                # not an array table
    "rule = \"GL001\"\n",                          # key outside table
    "[[suppress]]\nrule = \"GL001\"\npath = \"p\"\nreason = \"\"\n",
    "[[suppress]]\nrule = \"GL001\"\npath = \"p\"\ncount = 0\n"
    "reason = \"r\"\n",
    "[[suppress]]\npath = \"p\"\nreason = \"r\"\n",   # missing rule
])
def test_baseline_format_errors(bad):
    with pytest.raises(BaselineError):
        parse_baseline(bad)


# ---------------------------------------------------------------------------
# the gates themselves
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_package_tree_lints_clean():
    report = run_lint()
    assert report.ok, "\n".join(f.format() for f in report.unsuppressed)
    assert not report.stale, [s.reason for s in report.stale]
    assert report.files_checked > 30


@pytest.mark.lint
def test_cli_exit_codes(tmp_path, capsys):
    bad = tmp_path / "seeded.py"
    bad.write_text(GL001_BAD)
    assert lint_main([str(bad), "--no-vmem", "-q"]) == 1
    out = capsys.readouterr().out
    assert "GL001" in out and "seeded.py:6" in out
    good = tmp_path / "clean.py"
    good.write_text(GL001_GOOD)
    assert lint_main([str(good), "--no-vmem", "-q"]) == 0


@pytest.mark.lint
@pytest.mark.parametrize("snippet,rule", [
    (GL001_BAD, "GL001"), (GL002_BAD, "GL002"), (GL003_BAD, "GL003"),
    (GL004_BAD, "GL004"), (GL005_BAD, "GL005"), (GL006_BAD, "GL006"),
    (GL007_BAD, "GL007"), (GL008_BAD, "GL008"), (GL009_BAD, "GL009"),
    (GL011_BAD, "GL011"),
], ids=["GL001", "GL002", "GL003", "GL004", "GL005", "GL006", "GL007",
        "GL008", "GL009", "GL011"])
def test_cli_nonzero_per_seeded_rule(tmp_path, snippet, rule, capsys):
    p = tmp_path / f"{rule.lower()}.py"
    p.write_text(snippet)
    assert lint_main([str(p), "--no-vmem", "-q"]) == 1
    assert rule in capsys.readouterr().out


@pytest.mark.lint
def test_cli_format_github_annotations(tmp_path, capsys):
    p = tmp_path / "seeded.py"
    p.write_text(GL001_BAD)
    assert lint_main([str(p), "--no-vmem", "--format", "github"]) == 1
    out = capsys.readouterr().out
    first = out.splitlines()[0]
    assert first.startswith(f"::error file={p},line=6,col=")
    assert "title=graftlint GL001::" in first
    # clean tree -> no annotation lines at all
    g = tmp_path / "clean.py"
    g.write_text(GL001_GOOD)
    assert lint_main([str(g), "--no-vmem", "--no-baseline",
                      "--format", "github"]) == 0
    assert "::error" not in capsys.readouterr().out


@pytest.mark.lint
def test_cli_exit_2_usage_error(tmp_path, capsys):
    p = tmp_path / "x.py"
    p.write_text("x = 1\n")
    b = tmp_path / "bad.toml"
    b.write_text("[suppress]\n")            # not the array-table form
    assert lint_main([str(p), "--baseline", str(b),
                      "--no-vmem", "-q"]) == 2
    assert "graftlint: usage-error:" in capsys.readouterr().err


@pytest.mark.lint
def test_cli_exit_3_internal_error(tmp_path, capsys):
    # a directory where the baseline file should be -> IsADirectoryError
    # inside the analyzer; the CLI must report a typed one-liner and
    # exit 3, NOT pretend the tree has findings
    p = tmp_path / "x.py"
    p.write_text("x = 1\n")
    d = tmp_path / "bldir"
    d.mkdir()
    assert lint_main([str(p), "--baseline", str(d),
                      "--no-vmem", "-q"]) == 3
    err = capsys.readouterr().err
    assert err.startswith("graftlint: internal-error: IsADirectoryError")
    assert "Traceback" not in err


@pytest.mark.lint
def test_gl000_parse_failure_bypasses_waivers(tmp_path, capsys):
    bad = tmp_path / "broken.py"
    # the waiver comment is unreachable: the file does not parse
    bad.write_text("def f(:  # graftlint: GL000 — nope\n")
    assert lint_main([str(bad), "--no-baseline", "--no-vmem", "-q"]) == 1
    assert "GL000" in capsys.readouterr().out


@pytest.mark.lint
def test_gl000_baseline_attempt_is_a_usage_error(tmp_path, capsys):
    # r20: trying to BASELINE a parse failure is rejected when the
    # ledger is read, before any file is analyzed — exit 2, not a
    # silently-ignored entry
    bad = tmp_path / "broken.py"
    bad.write_text("def f(:\n")
    b = tmp_path / "b.toml"
    b.write_text(f'[[suppress]]\nrule = "GL000"\npath = "{bad}"\n'
                 f'count = 5\nreason = "trying to baseline a parse "\n')
    assert lint_main([str(bad), "--baseline", str(b),
                      "--no-vmem", "-q"]) == 2
    assert "never baselineable" in capsys.readouterr().err


def test_vmem_specs_fit_budget():
    from lightgbm_tpu.analysis.vmem import check_vmem_specs

    for r in check_vmem_specs():
        assert r["ok"], r
        assert r["estimated_mb"] > 0.1, r      # the model isn't vacuous


@pytest.mark.lint
def test_serving_recompile_sweep():
    from lightgbm_tpu.analysis.budgets import serving_recompile_sweep

    r = serving_recompile_sweep(max_bucket=64)
    assert r["ok"], r
    assert r["compiles"] <= 7 and r["recompiles_on_repeat"] == 0


@pytest.mark.lint
def test_fused_train_step_single_compile():
    from lightgbm_tpu.analysis.budgets import fused_train_step_recompiles

    r = fused_train_step_recompiles(n_hyper_batches=3)
    assert r["ok"], r
    assert r["compiles"] <= 1


# ---------------------------------------------------------------------------
# r20 tentpole: GL012 mesh/collective discipline
# ---------------------------------------------------------------------------

GL012_BAD = """\
import jax
from jax import lax

def merge(hist):
    return lax.psum(hist, "data")
"""

GL012_GOOD = """\
import jax
from jax import lax

def merge(hist, axis_name):
    return lax.psum(hist, axis_name)
"""

GL012_MISMATCH = """\
import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def step(v):
    return lax.psum(v, "rows")

def run(mesh, x):
    f = shard_map(step, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    return f(x)
"""

GL012_NESTED_GOOD = """\
import jax
from jax import lax
from jax.experimental.shard_map import shard_map
from jax.sharding import PartitionSpec as P

def run(mesh, x):
    def body(v):
        return lax.psum(v, "data")
    f = shard_map(body, mesh=mesh, in_specs=P("data"),
                  out_specs=P("data"))
    return f(x)
"""

GL012_COND_BAD = """\
import jax
from jax import lax

@jax.jit
def maybe_merge(pred, v, axis_name):
    return lax.cond(pred,
                    lambda t: lax.psum(t, axis_name),
                    lambda t: t, v)
"""

GL012_COND_GOOD = """\
import jax
from jax import lax

@jax.jit
def maybe_merge(pred, v, axis_name):
    return lax.cond(pred,
                    lambda t: lax.psum(t, axis_name),
                    lambda t: lax.psum(t * 0, axis_name), v)
"""


def test_gl012_collective_outside_mesh():
    # literal axis, no shard_map/pmap reaches merge() -> SPMD hang shape
    assert rules_at(GL012_BAD, "GL012") == [line_of(GL012_BAD, "psum")]


def test_gl012_parameter_axis_is_sanctioned():
    # the caller owns the binding: a helper taking axis_name= never fires
    assert rules_at(GL012_GOOD, "GL012") == []


def test_gl012_axis_name_disagrees_with_mesh_specs():
    lines = rules_at(GL012_MISMATCH, "GL012")
    assert lines == [line_of(GL012_MISMATCH, '"rows"')]
    fs = [f for f in findings(GL012_MISMATCH) if f.rule == "GL012"]
    assert "'rows'" in fs[0].message and "'data'" in fs[0].message
    good = GL012_MISMATCH.replace('"rows"', '"data"')
    assert rules_at(good, "GL012") == []


def test_gl012_nested_closure_idiom_is_meshed():
    # the standard spelling: the collective-bearing body is a def NESTED
    # in the function that calls shard_map on it — in-mesh, silent
    assert rules_at(GL012_NESTED_GOOD, "GL012") == []
    # ...and the same nesting with the wrong axis still fires mismatch
    wrong = GL012_NESTED_GOOD.replace('lax.psum(v, "data")',
                                      'lax.psum(v, "rows")')
    assert rules_at(wrong, "GL012") == [line_of(wrong, '"rows"')]


def test_gl012_inline_lambda_entry_is_meshed():
    src = ("import jax\nfrom jax import lax\n"
           "from jax.experimental.shard_map import shard_map\n"
           "from jax.sharding import PartitionSpec as P\n\n"
           "def run(mesh, x):\n"
           '    return shard_map(lambda v: lax.psum(v, "data"),\n'
           '                     mesh=mesh, in_specs=P("data"),\n'
           '                     out_specs=P("data"))(x)\n')
    assert rules_at(src, "GL012") == []
    wrong = src.replace('"data"),\n                     mesh',
                        '"rows"),\n                     mesh')
    assert rules_at(wrong, "GL012") == [line_of(wrong, '"rows"')]


def test_gl012_unbalanced_cond_collective():
    # one branch psums, the other doesn't: half the mesh enters the
    # collective, the other half never will — the deadlock shape
    assert rules_at(GL012_COND_BAD, "GL012") == [
        line_of(GL012_COND_BAD, "lax.cond")]
    fs = [f for f in findings(GL012_COND_BAD) if f.rule == "GL012"]
    assert "branch" in fs[0].message


def test_gl012_lockstep_cond_twin_is_silent():
    # both branches perform a collective -> lock-step, no finding
    assert rules_at(GL012_COND_GOOD, "GL012") == []


def test_gl012_axis_resolves_through_module_constant():
    src = ("import jax\nfrom jax import lax\n"
           "from jax.experimental.shard_map import shard_map\n"
           "from jax.sharding import PartitionSpec as P\n\n"
           'DATA_AXIS = "data"\n\n'
           "def step(v):\n"
           "    return lax.psum(v, DATA_AXIS)\n\n"
           "def run(mesh, x):\n"
           "    return shard_map(step, mesh=mesh, in_specs=P(DATA_AXIS),\n"
           "                     out_specs=P(DATA_AXIS))(x)\n")
    assert rules_at(src, "GL012") == []
    # the constant resolving to a NON-mesh axis fires the mismatch
    wrong = src.replace('DATA_AXIS = "data"\n\n',
                        'DATA_AXIS = "data"\nROW_AXIS = "rows"\n\n').replace(
        "lax.psum(v, DATA_AXIS)", "lax.psum(v, ROW_AXIS)")
    assert rules_at(wrong, "GL012") == [line_of(wrong, "ROW_AXIS)")]


def test_gl012_unresolvable_mesh_axes_disable_agreement_only():
    # specs built from a runtime value: membership holds (no
    # outside-mesh finding) but the axis-agreement check stands down
    src = ("import jax\nfrom jax import lax\n"
           "from jax.experimental.shard_map import shard_map\n"
           "from jax.sharding import PartitionSpec as P\n\n"
           "def step(v):\n"
           '    return lax.psum(v, "whatever")\n\n'
           "def run(smesh, x):\n"
           "    return shard_map(step, mesh=smesh.mesh,\n"
           "                     in_specs=P(smesh.axis_name),\n"
           "                     out_specs=P(smesh.axis_name))(x)\n")
    assert rules_at(src, "GL012") == []


def test_cross_module_mesh_closure():
    # the collective helper lives in another FILE; only the Program
    # closure can see the shard_map entry that meshes it — and the
    # axis constant resolves through the import table
    axes = 'DATA_AXIS = "data"\n'
    helper = ("from jax import lax\nfrom pkg.axes import DATA_AXIS\n\n"
              "def merge(hist):\n"
              "    return lax.psum(hist, DATA_AXIS)\n")
    entry = ("import jax\n"
             "from jax.experimental.shard_map import shard_map\n"
             "from jax.sharding import PartitionSpec as P\n"
             "from pkg.axes import DATA_AXIS\n"
             "from pkg.helper import merge\n\n"
             "def run(mesh, x):\n"
             "    return shard_map(merge, mesh=mesh,\n"
             "                     in_specs=P(DATA_AXIS),\n"
             "                     out_specs=P(DATA_AXIS))(x)\n")
    mods = [("pkg/axes.py", axes), ("pkg/helper.py", helper),
            ("pkg/entry.py", entry)]
    assert prog_findings(mods, "GL012") == []
    # per-file analysis of the helper ALONE flags the psum as
    # outside-mesh; the entry module is what sanctions it
    assert prog_findings(mods[:2], "GL012") != []
    # and a wrong axis still fires THROUGH the closure, in the helper
    bad = [("pkg/axes.py", axes + 'ROW_AXIS = "rows"\n'),
           ("pkg/helper.py", helper.replace("DATA_AXIS", "ROW_AXIS")),
           ("pkg/entry.py", entry)]
    fs = prog_findings(bad, "GL012")
    assert [(f.path, f.line) for f in fs] == [("pkg/helper.py", 5)]


# ---------------------------------------------------------------------------
# r20 tentpole: GL013 quantized-space discipline
# ---------------------------------------------------------------------------

GL013_BAD = """\
import jax.numpy as jnp

def route(rows, thresholds, scale):
    codes = rows.astype(jnp.uint8)
    deq = thresholds.astype(jnp.float32) * scale
    return codes <= deq
"""

GL013_GOOD = """\
import jax.numpy as jnp

def route(rows, thresholds):
    codes = rows.astype(jnp.uint8)
    cuts = thresholds.astype(jnp.uint8)
    return codes <= cuts
"""

GL013_ACC_BAD = """\
import jax.numpy as jnp
from jax import lax

def accumulate(onehot, grads):
    oh = onehot.astype(jnp.int8)
    q = grads.astype(jnp.int8)
    return lax.dot_general(oh, q, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)
"""

GL013_ACC_GOOD = """\
import jax.numpy as jnp
from jax import lax

INT8_ACC_ROW_LIMIT = (1 << 31) // 127

def accumulate(onehot, grads, n):
    if n > INT8_ACC_ROW_LIMIT:
        raise ValueError("int8 accumulation overflows past the limit")
    oh = onehot.astype(jnp.int8)
    q = grads.astype(jnp.int8)
    return lax.dot_general(oh, q, (((0,), (0,)), ((), ())),
                           preferred_element_type=jnp.int32)
"""

GL013_HOP_BAD = """\
import jax.numpy as jnp
from jax import lax

def ring_send(payload, perm, axis_name):
    q = payload.astype(jnp.int8)
    return lax.ppermute(q, axis_name, perm)
"""


def test_gl013_bin_code_vs_dequantized_mix():
    # u8 bin codes compared against f32 values: off-by-one routing
    # vs the quantized-space contract (PARITY.md r18)
    assert rules_at(GL013_BAD, "GL013") == [
        line_of(GL013_BAD, "codes <= deq")]
    fs = [f for f in findings(GL013_BAD) if f.rule == "GL013"]
    assert "bin" in fs[0].message


def test_gl013_same_space_comparison_is_silent():
    assert rules_at(GL013_GOOD, "GL013") == []


def test_gl013_bin_vs_float_literal_fires_int_is_fine():
    lit = ("import jax.numpy as jnp\n\ndef f(rows):\n"
           "    codes = rows.astype(jnp.uint8)\n"
           "    return codes <= 0.5\n")
    assert rules_at(lit, "GL013") == [line_of(lit, "0.5")]
    # an INT literal is a valid bin code — stays silent
    assert rules_at(lit.replace("0.5", "255"), "GL013") == []


def test_gl013_stat_space_is_absorbing_through_binop():
    # f32 * unknown promotes to f32 (JAX promotion): the mix must
    # still be proven through the arithmetic
    src = GL013_BAD.replace("thresholds.astype(jnp.float32) * scale",
                            "scale * thresholds.astype(jnp.float32)")
    assert rules_at(src, "GL013") == [line_of(src, "codes <= deq")]


def test_gl013_unguarded_int8_accumulation():
    assert rules_at(GL013_ACC_BAD, "GL013") == [
        line_of(GL013_ACC_BAD, "dot_general")]
    fs = [f for f in findings(GL013_ACC_BAD) if f.rule == "GL013"]
    assert "16,909,320" in fs[0].message or "16909320" in fs[0].message


def test_gl013_guarded_int8_accumulation_twin_is_silent():
    # the module carries the (1 << 31) // 127 row-count guard the rule
    # demands -> silent
    assert rules_at(GL013_ACC_GOOD, "GL013") == []


def test_gl013_wire_payload_hop_outside_requantize_boundary():
    assert rules_at(GL013_HOP_BAD, "GL013") == [
        line_of(GL013_HOP_BAD, "ppermute")]
    # inside the sanctioned boundary (wire_transfer) the hop is THE
    # requantize point — silent
    good = GL013_HOP_BAD.replace("def ring_send", "def wire_transfer")
    assert rules_at(good, "GL013") == []
    # an f32 payload needs no requantize — silent
    f32 = GL013_HOP_BAD.replace("jnp.int8", "jnp.float32")
    assert rules_at(f32, "GL013") == []


# ---------------------------------------------------------------------------
# r20 tentpole: GL014 parity-contract anchors
# ---------------------------------------------------------------------------

def test_gl014_real_tree_anchors_all_live():
    from lightgbm_tpu.analysis.engine import REPO_ROOT
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    assert parity_anchor_findings(REPO_ROOT) == []


def test_gl014_dead_symbol_fails_the_contract():
    from lightgbm_tpu.analysis.engine import REPO_ROOT
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    anchors = {"Quantized-threshold comparison rule (r18 serving)": (
        ("lightgbm_tpu/ops/predict.py", "predict_forest_pallas_v2"),)}
    fs = parity_anchor_findings(REPO_ROOT, anchors=anchors)
    dead = [f for f in fs if "no longer exists" in f.message]
    assert len(dead) == 1 and dead[0].rule == "GL014"
    assert "predict_forest_pallas_v2" in dead[0].message
    assert dead[0].path == "PARITY.md" and dead[0].line > 1


def test_gl014_missing_module_fails_the_contract():
    from lightgbm_tpu.analysis.engine import REPO_ROOT
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    anchors = {"Quantized-threshold comparison rule (r18 serving)": (
        ("lightgbm_tpu/ops/gone.py", "predict_forest_pallas"),)}
    fs = parity_anchor_findings(REPO_ROOT, anchors=anchors)
    gone = [f for f in fs if "missing or unparseable" in f.message]
    assert len(gone) == 1 and "ops/gone.py" in gone[0].message


def test_gl014_stale_anchor_key_fires():
    from lightgbm_tpu.analysis.engine import REPO_ROOT
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    anchors = {"A contract heading that was renamed away": ()}
    fs = parity_anchor_findings(REPO_ROOT, anchors=anchors)
    stale = [f for f in fs if "no such heading" in f.message]
    assert len(stale) == 1 and stale[0].line == 1


def test_gl014_unanchored_claim_fires_at_its_heading():
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    doc = ("# parity\n\n## Some new kernel rule\n\n"
           "The fused path is bit-identical to the scan path.\n")
    fs = parity_anchor_findings("/nonexistent", anchors={}, parity_md=doc)
    assert [(f.line, f.rule) for f in fs] == [(3, "GL014")]
    assert "no PARITY_ANCHORS entry" in fs[0].message


def test_gl014_table_rows_are_not_claims():
    from lightgbm_tpu.analysis.program import parity_anchor_findings

    doc = ("# parity\n\n## Feature inventory\n\n"
           "| knob | behavior |\n|---|---|\n"
           "| unknown-param tolerance | warn |\n")
    assert parity_anchor_findings("/x", anchors={}, parity_md=doc) == []


def test_gl014_missing_parity_doc_with_live_anchors():
    from lightgbm_tpu.analysis.program import (PARITY_ANCHORS,
                                               parity_anchor_findings)

    fs = parity_anchor_findings("/nonexistent", anchors=PARITY_ANCHORS)
    assert len(fs) == 1 and "missing" in fs[0].message
    assert fs[0].line == 1


# ---------------------------------------------------------------------------
# r20 satellites: --explain, baseline rule-id validation, CLI coverage
# ---------------------------------------------------------------------------

@pytest.mark.lint
def test_cli_explain_prints_rules_md_section(capsys):
    assert lint_main(["--explain", "GL013"]) == 0
    out = capsys.readouterr().out
    assert out.startswith("## GL013")
    assert "quantized-space" in out
    # the section is cut at the NEXT heading — no bleed-through
    assert "GL014" not in out.replace("GL013", "")


@pytest.mark.lint
def test_cli_explain_unknown_rule_is_usage_error(capsys):
    assert lint_main(["--explain", "GL099"]) == 2
    err = capsys.readouterr().err
    assert err.startswith("graftlint: usage-error:")
    assert "GL099" in err and "GL012" in err   # lists the known ids


@pytest.mark.lint
def test_cli_explain_requires_an_argument(capsys):
    assert lint_main(["--explain"]) == 2
    assert "usage-error" in capsys.readouterr().err


@pytest.mark.parametrize("rule,msg", [
    ("GL9999", "malformed"), ("bogus", "malformed"),
    ("GL999", "unknown rule id"), ("GL000", "never baselineable"),
])
def test_baseline_rejects_bad_rule_ids(rule, msg):
    with pytest.raises(BaselineError, match=msg):
        parse_baseline(f'[[suppress]]\nrule = "{rule}"\n'
                       f'path = "p.py"\ncount = 1\nreason = "r"\n')


@pytest.mark.lint
@pytest.mark.parametrize("snippet,rule", [
    (GL012_BAD, "GL012"), (GL013_BAD, "GL013"),
], ids=["GL012", "GL013"])
def test_cli_nonzero_per_r20_seeded_rule(tmp_path, snippet, rule, capsys):
    p = tmp_path / f"{rule.lower()}.py"
    p.write_text(snippet)
    assert lint_main([str(p), "--no-vmem", "--no-baseline", "-q"]) == 1
    assert rule in capsys.readouterr().out


@pytest.mark.lint
def test_seeded_fixture_matches_check_sh_expectations(capsys):
    # tools/check.sh greps for these exact annotations; keep the fixture
    # and the lane in lock-step
    from lightgbm_tpu.analysis.engine import REPO_ROOT

    fx = os.path.join(REPO_ROOT, "tests", "fixtures",
                      "graftlint_seeded.py")
    assert lint_main([fx, "--no-vmem", "--no-baseline",
                      "--format", "github", "-q"]) == 1
    out = capsys.readouterr().out
    assert "title=graftlint GL012::" in out
    assert "title=graftlint GL013::" in out


def test_mesh_probe_shim_reexports():
    # tools/hlo_counts.py re-exports the GL012 probe surface; the probe
    # itself reports meshed functions with their collectives
    import importlib.util
    from lightgbm_tpu.analysis.engine import REPO_ROOT

    spec = importlib.util.spec_from_file_location(
        "hlo_counts", os.path.join(REPO_ROOT, "tools", "hlo_counts.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    assert "psum" in mod.COLLECTIVE_CALLS
    assert "shard_map" in mod.MESH_ENTRY_CALLS
    probe = mod.mesh_probe(
        "fix.py", src=GL012_MISMATCH)
    by_name = {p["function"]: p for p in probe}
    assert by_name["step"]["meshed"]
    assert by_name["step"]["axes"] == ["data"]
    assert [c["op"] for c in by_name["step"]["collectives"]] == ["psum"]

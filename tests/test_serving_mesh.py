"""Pod-scale serving: mesh-sharded prediction + quantized PackedForest.

Covers the r14 acceptance surface: the deterministic route chooser and
its dp row-tile floor, dp bit-identity vs the single-device runtime
across batch shapes (ragged tails included) on the virtual CPU mesh, tp
``psum`` parity within a few ulp (with ``num_iteration`` truncation and
multiclass), warm() coverage of shard programs (zero traffic-path
compiles), the shared quantizer (wire shim re-exports, exact
threshold-bound guards, per-tree int8 scales, models-per-byte gains),
the two-gate quantized canary, and the r12 chaos matrix re-run with the
mesh active: hot swap, rollback, device-fault fallback and the CLI
SIGTERM drain — all with mesh/precision serve keys.

Mesh programs compile against the 8 virtual CPU devices conftest forces
via ``xla_force_host_platform_device_count``; models stay tiny because
shard_map compiles dominate wall time here.
"""

import io
import json
import signal

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.ops import quantize as qz
from lightgbm_tpu.serving import (
    FaultInjector,
    MicroBatcher,
    ModelBank,
    PackedForest,
    PredictorRuntime,
    SwapRejected,
    ThresholdBoundError,
    pack_booster,
)
from lightgbm_tpu.serving.mesh import (
    DP_MIN_ROWS_PER_SHARD,
    ServingMesh,
    choose_route,
)


# ---------------------------------------------------------------------------
# fixtures (tiny models, small buckets: shard_map compiles dominate)
# ---------------------------------------------------------------------------
@pytest.fixture(scope="module")
def mesh_models(small_regression, tmp_path_factory):
    """(X, v1_path, v2_path): two same-feature-count regression models
    with different predictions, saved as serving artifacts."""
    X, y = small_regression
    d = tmp_path_factory.mktemp("mesh")
    b1 = lgb.train(
        {"objective": "regression", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=12)
    b2 = lgb.train(
        {"objective": "regression", "num_leaves": 7, "verbosity": -1},
        lgb.Dataset(X, label=np.asarray(X[:, 0], np.float64)),
        num_boost_round=4)
    v1, v2 = str(d / "v1.npz"), str(d / "v2.npz")
    pack_booster(b1).save(v1)
    pack_booster(b2).save(v2)
    return X, v1, v2


@pytest.fixture(scope="module")
def mc_packed():
    rng = np.random.default_rng(7)
    n = 600
    X = rng.normal(size=(n, 4))
    y = ((X[:, 0] + X[:, 1] > 0).astype(int)
         + (X[:, 2] > 0.5).astype(int)).astype(np.float64)
    b = lgb.train(
        {"objective": "multiclass", "num_class": 3, "num_leaves": 7,
         "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=4)
    return X, pack_booster(b)


@pytest.fixture(scope="module")
def binary_packed(small_binary):
    X, y = small_binary
    b = lgb.train(
        {"objective": "binary", "num_leaves": 15, "verbosity": -1},
        lgb.Dataset(X, label=y), num_boost_round=15)
    return X, y, pack_booster(b)


def _ulp_tol(ref, ulps=2):
    return ulps * np.spacing(np.float32(np.max(np.abs(ref))))


# ---------------------------------------------------------------------------
# route chooser + mesh validation (pure functions, no compiles)
# ---------------------------------------------------------------------------
def test_choose_route_matrix():
    floor = DP_MIN_ROWS_PER_SHARD
    # one device: always single, whatever the policy asks for
    for pol in ("auto", "dp", "tp"):
        assert choose_route(pol, 256, 500, 1) == "single"
    # dp engages only at a full row tile per shard
    assert choose_route("dp", 4 * floor, 100, 4) == "dp"
    assert choose_route("dp", 4 * floor - 1, 100, 4) == "single"
    assert choose_route("dp", 2 * floor, 100, 4) == "single"
    # tp needs a tree per device
    assert choose_route("tp", 8, 100, 8) == "tp"
    assert choose_route("tp", 8, 7, 8) == "single"
    # auto: small bucket + splittable forest -> tp; big bucket -> dp;
    # neither -> single
    assert choose_route("auto", 16, 100, 4) == "tp"
    assert choose_route("auto", 256, 100, 4) == "dp"
    assert choose_route("auto", 16, 4, 4) == "single"
    # auto never picks dp below the tile floor (and won't promote a
    # 4-tree forest to tp either)
    assert choose_route("auto", 64, 4, 8) == "single"
    assert choose_route("auto", 8 * floor, 4, 8) == "dp"
    with pytest.raises(ValueError, match="shard_policy"):
        choose_route("both", 64, 100, 4)


def test_mesh_and_runtime_validation(mesh_models):
    _, v1, _ = mesh_models
    with pytest.raises(ValueError, match="power of two"):
        ServingMesh(3)
    pf = PackedForest.load(v1)
    with pytest.raises(ValueError, match="power of two"):
        PredictorRuntime(pf, mesh_devices=3)
    with pytest.raises(ValueError, match="shard_policy"):
        PredictorRuntime(pf, mesh_devices=2, shard_policy="maybe")
    with pytest.raises(ValueError, match="forest_precision"):
        PredictorRuntime(pf, forest_precision="fp4")


# ---------------------------------------------------------------------------
# dp: bit-identity vs the single-device runtime
# ---------------------------------------------------------------------------
def test_dp_bit_identical_across_shapes(mesh_models):
    X, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    single = PredictorRuntime(pf, max_bucket=256)
    for d in (2, 4):
        rt = PredictorRuntime(pf, max_bucket=256, mesh_devices=d,
                              shard_policy="dp")
        for n in (1, 17, 16 * d, 137):       # ragged tails + exact tile
            got = rt.predict(X[:n])
            assert np.array_equal(got, single.predict(X[:n])), (d, n)
        assert "dp" in rt.cache_info()["routes_live"]


def test_dp_bit_identical_d8_and_num_iteration(mesh_models):
    X, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    single = PredictorRuntime(pf, max_bucket=256)
    rt = PredictorRuntime(pf, max_bucket=256, mesh_devices=8,
                          shard_policy="dp")
    assert rt.route_for(256) == "dp" and rt.route_for(64) == "single"
    for k in (None, 5):
        got = rt.predict(X[:137], num_iteration=k)
        assert np.array_equal(got, single.predict(X[:137],
                                                  num_iteration=k))


def test_dp_multiclass_bit_identical(mc_packed):
    X, pf = mc_packed
    single = PredictorRuntime(pf, max_bucket=128)
    rt = PredictorRuntime(pf, max_bucket=128, mesh_devices=4,
                          shard_policy="dp")
    got = rt.predict(X[:97])
    assert got.shape == (97, 3)
    assert np.array_equal(got, single.predict(X[:97]))


# ---------------------------------------------------------------------------
# tp: psum parity within a few ulp
# ---------------------------------------------------------------------------
def test_tp_parity_within_ulp(mesh_models):
    X, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    single = PredictorRuntime(pf, max_bucket=64)
    rt = PredictorRuntime(pf, max_bucket=64, mesh_devices=4,
                          shard_policy="tp")
    ref = single.predict(X[:16])
    got = rt.predict(X[:16])
    assert np.max(np.abs(got - ref)) <= _ulp_tol(ref)
    assert rt.cache_info()["routes_live"] == ["tp"]


def test_tp_truncation_window(mesh_models):
    """tp maps the global ``num_iteration`` window into local tree
    coordinates per shard — truncated replay must match single-device
    truncation, not silently use the full forest."""
    X, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    single = PredictorRuntime(pf, max_bucket=32)
    rt = PredictorRuntime(pf, max_bucket=32, mesh_devices=4,
                          shard_policy="tp")
    for k in (1, 5, pf.num_trees):
        ref = single.predict(X[:8], num_iteration=k)
        got = rt.predict(X[:8], num_iteration=k)
        assert np.max(np.abs(got - ref)) <= _ulp_tol(ref), k


def test_tp_multiclass_parity(mc_packed):
    X, pf = mc_packed
    single = PredictorRuntime(pf, max_bucket=32)
    rt = PredictorRuntime(pf, max_bucket=32, mesh_devices=2,
                          shard_policy="tp")
    ref = single.predict(X[:8])
    got = rt.predict(X[:8])
    assert np.max(np.abs(got - ref)) <= _ulp_tol(ref)


# ---------------------------------------------------------------------------
# warm coverage: zero traffic-path compiles with shard routes live
# ---------------------------------------------------------------------------
def test_warm_covers_shard_programs(mesh_models):
    X, v1, _ = mesh_models
    rt = PredictorRuntime(PackedForest.load(v1), max_bucket=128,
                          mesh_devices=4, shard_policy="dp")
    rt.warm()
    info0 = rt.cache_info()
    assert info0["shard_programs"] > 0
    for n in (3, 64, 100):                # single + dp routes
        rt.predict(X[:n])
    info1 = rt.cache_info()
    assert info1["num_compiles"] == info0["num_compiles"]
    assert info1["mesh_devices"] == 4
    snap = rt.stats.snapshot()
    assert snap["compile_cache"]["shard_programs"] == info1[
        "shard_programs"]
    assert snap["route_dispatches"].get("dp", 0) > 0


# ---------------------------------------------------------------------------
# shared quantizer: wire shim, guards, scales, byte gains
# ---------------------------------------------------------------------------
def test_wire_shim_reexports_shared_quantizer():
    from lightgbm_tpu.ops import histogram

    assert histogram._wire_transfer is qz.wire_transfer
    assert histogram.WIRE_DTYPES is qz.WIRE_DTYPES
    assert qz.WIRE_DTYPES == ("f32", "bf16", "int8")


def test_quantize_forest_scales_and_bound(mesh_models):
    _, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    q = qz.quantize_forest(pf.split_feature, pf.split_bin, pf.left,
                           pf.right, pf.leaf_value, pf.is_leaf, "int8")
    deq = q.dequantized_leaf_values()
    real = np.where(pf.is_leaf, pf.leaf_value, 0.0)
    err = np.abs(np.where(pf.is_leaf, deq, 0.0) - real)
    # per-tree symmetric scales: every leaf within half a quantum
    assert np.all(err <= 0.5 * q.leaf_scale[:, None] + 1e-12)
    # the advertised bound dominates the worst per-row sum of errors
    assert q.error_bound >= float(np.max(np.sum(err, axis=-1))) - 1e-12
    assert q.leaf_q.dtype == np.int8


def test_quantize_threshold_bound_hard_error(mesh_models):
    _, v1, _ = mesh_models
    pf = PackedForest.load(v1)
    bad_bin = pf.split_bin.copy()
    bad_bin[0, int(np.argmin(pf.is_leaf[0]))] = 300
    with pytest.raises(ThresholdBoundError, match="split_bin"):
        qz.quantize_forest(pf.split_feature, bad_bin, pf.left, pf.right,
                           pf.leaf_value, pf.is_leaf, "int8")


def test_models_per_byte_gains():
    assert qz.models_per_byte_gain("int8") >= 1.9
    assert qz.models_per_byte_gain("bf16") >= 1.5
    f32 = qz.packed_model_bytes(200, 509, precision="f32")
    i8 = qz.packed_model_bytes(200, 509, precision="int8")
    assert f32 / i8 >= 1.9


# ---------------------------------------------------------------------------
# quantized runtime: drift bounded by its own arithmetic bound + AUC
# ---------------------------------------------------------------------------
def _auc(y, s):
    y = np.asarray(y, bool)
    order = np.argsort(s, kind="mergesort")
    ranks = np.empty(len(s), np.float64)
    ss = np.asarray(s, np.float64)[order]
    i = 0
    while i < len(s):
        j = i
        while j + 1 < len(s) and ss[j + 1] == ss[i]:
            j += 1
        ranks[order[i:j + 1]] = 0.5 * ((i + 1) + (j + 1))
        i = j + 1
    n_pos = int(y.sum())
    return float((ranks[y].sum() - n_pos * (n_pos + 1) / 2.0)
                 / (n_pos * (len(y) - n_pos)))


def test_int8_margin_drift_and_auc(binary_packed):
    X, y, pf = binary_packed
    n = 1000
    ref = PredictorRuntime(pf, max_bucket=256).predict(
        X[:n], raw_score=True)
    for prec in ("bf16", "int8"):
        rt = PredictorRuntime(pf, max_bucket=256, forest_precision=prec)
        got = rt.predict(X[:n], raw_score=True)
        assert np.max(np.abs(got - ref)) <= rt.quant_error_bound, prec
        assert abs(_auc(y[:n], got) - _auc(y[:n], ref)) <= 1e-4, prec
        # degraded-mode fallback answers come from the dequantized
        # oracle, i.e. they match device arithmetic, not exact f32
        oracle = rt.oracle.predict_numpy(
            rt.packed.bin_mapper.transform(np.asarray(X[:8], np.float64)),
            raw_score=True)
        dev = rt.predict(X[:8], raw_score=True)
        assert np.max(np.abs(dev - oracle)) <= 1e-5, prec


def test_quantized_dp_matches_quantized_single(binary_packed):
    X, _, pf = binary_packed
    single = PredictorRuntime(pf, max_bucket=128, forest_precision="int8")
    rt = PredictorRuntime(pf, max_bucket=128, mesh_devices=4,
                          shard_policy="dp", forest_precision="int8")
    assert np.array_equal(rt.predict(X[:128]), single.predict(X[:128]))


# ---------------------------------------------------------------------------
# chaos matrix with the mesh active (the r12 contracts must survive)
# ---------------------------------------------------------------------------
def _mesh_bank(**kw):
    kw.setdefault("max_bucket", 128)
    kw.setdefault("canary_rows", 4)
    kw.setdefault("mesh_devices", 4)
    kw.setdefault("shard_policy", "dp")
    return ModelBank(**kw)


def test_bank_quantized_canary_two_gates(mesh_models):
    _, v1, _ = mesh_models
    bank = _mesh_bank(forest_precision="int8", warm_on_deploy=False)
    rep = bank.deploy("m", v1)
    assert rep["canary"]["quant_abs_err"] <= rep["canary"][
        "quant_error_bound"]


def test_bank_threshold_bound_rejected_at_build(mesh_models, tmp_path):
    import copy

    _, v1, _ = mesh_models
    bad = copy.deepcopy(PackedForest.load(v1))
    bad.split_bin = bad.split_bin.astype(np.int32)
    bad.split_bin[0, int(np.argmin(bad.is_leaf[0]))] = 300
    bad_path = str(tmp_path / "bad_bin.npz")
    bad.save(bad_path)
    bank = ModelBank(max_bucket=32, canary_rows=4, warm_on_deploy=False,
                     forest_precision="int8")
    with pytest.raises(SwapRejected, match="build"):
        bank.deploy("m", bad_path)


def test_mesh_hot_swap_atomic_for_queued_traffic(mesh_models):
    X, v1, v2 = mesh_models
    bank = _mesh_bank(warm_on_deploy=False)
    bank.deploy("m", v1)
    t = [0.0]
    mb = bank.batcher("m", max_batch=4, max_delay_ms=5.0,
                      clock=lambda: t[0])
    v1_single = PredictorRuntime(PackedForest.load(v1), max_bucket=128)
    v2_single = PredictorRuntime(PackedForest.load(v2), max_bucket=128)
    pre = [mb.submit(X[i]) for i in range(3)]
    bank.deploy("m", v2)                  # swap with requests queued
    post = [mb.submit(X[i]) for i in range(3)]
    t[0] += 1.0
    mb.pump(); mb.flush()
    got = np.array([h.result() for h in pre + post])
    want_v2 = v2_single.predict(X[:3])
    # queued traffic resolves the bank at DISPATCH: one atomic flip
    # moved every device's programs to v2, nothing failed or forked
    assert np.array_equal(got[3:], want_v2)
    assert all(np.array_equal(g, a) or np.array_equal(g, b)
               for g, a, b in zip(got[:3], v1_single.predict(X[:3]),
                                  want_v2))


def test_mesh_rollback_bit_identical(mesh_models, tmp_path):
    import copy

    X, v1, v2 = mesh_models
    bank = _mesh_bank(warm_on_deploy=False)
    bank.deploy("m", v1)
    before = bank.predict("m", X[:64])
    bank.deploy("m", v2)
    bad = copy.deepcopy(PackedForest.load(v1))
    bad.left[0, 0] = 0                    # cycle -> ingest rejection
    bad_path = str(tmp_path / "cycle.npz")
    bad.save(bad_path)
    with pytest.raises(SwapRejected, match="ingest"):
        bank.deploy("m", bad_path)
    assert bank.version("m") == "v2"
    rb = bank.rollback("m")
    assert rb["version"] == "v1"
    assert np.array_equal(bank.predict("m", X[:64]), before)


def test_mesh_device_fault_falls_back_to_oracle(mesh_models):
    X, v1, _ = mesh_models
    bank = _mesh_bank(warm_on_deploy=False, forest_precision="int8")
    bank.deploy("m", v1)
    rt = bank.runtime("m")
    inj = FaultInjector()
    inj.arm("device_predict", after=0, times=1, message="mesh boom")
    rt.faults = inj
    t = [0.0]
    mb = bank.batcher("m", max_batch=4, max_delay_ms=5.0,
                      clock=lambda: t[0])
    handles = [mb.submit(X[i]) for i in range(4)]
    mb.pump(); mb.flush()
    got = np.array([h.result() for h in handles])
    # degraded answers come from the dequantized oracle — the same
    # arithmetic the device route serves, so the fallback is seamless
    want = rt.oracle.predict_numpy(
        rt.packed.bin_mapper.transform(np.asarray(X[:4], np.float64)),
        raw_score=False)
    assert np.allclose(got, want, atol=1e-6)
    assert mb.stats.snapshot()["fallbacks"] > 0


# ---------------------------------------------------------------------------
# CLI serve keys + SIGTERM drain with the mesh active
# ---------------------------------------------------------------------------
def _run_serve(path, cfg, lines):
    from lightgbm_tpu.__main__ import _serve

    out, err = io.StringIO(), io.StringIO()
    rc = _serve(path, dict(cfg), stdin=iter(lines), stdout=out,
                stderr=err)
    return rc, out.getvalue().splitlines(), err.getvalue()


def test_cli_serve_rejects_bad_mesh_keys(mesh_models):
    from lightgbm_tpu.__main__ import _serve

    _, v1, _ = mesh_models
    for cfg, msg in (
            ({"mesh_devices": "3"}, "mesh_devices"),
            ({"mesh_devices": "lots"}, "mesh_devices"),
            ({"shard_policy": "sometimes"}, "shard_policy"),
            ({"forest_precision": "fp4"}, "forest_precision"),
    ):
        with pytest.raises(SystemExit, match=msg):
            _serve(v1, cfg, stdin=iter(()), stdout=io.StringIO(),
                   stderr=io.StringIO())


def test_cli_serve_mesh_sigterm_drains(mesh_models):
    """SIGTERM mid-stream with mesh + int8 active: admitted requests are
    answered from the sharded quantized runtime, the drain contract is
    unchanged from r12."""
    X, v1, _ = mesh_models
    rows = [",".join(f"{x:.8g}" for x in X[i]) for i in range(3)]

    def feed():
        yield rows[0] + "\n"
        yield rows[1] + "\n"
        signal.raise_signal(signal.SIGTERM)
        yield rows[2] + "\n"
    rc, out, err = _run_serve(
        v1, {"mesh_devices": "4", "shard_policy": "dp",
             "forest_precision": "int8", "canary_rows": "4"}, feed())
    assert rc == 0
    assert len(out) == 2 and "ERROR" not in "".join(out)
    assert "drained on SIGTERM" in err
    final = json.loads(err.strip().splitlines()[-1])
    assert final["requests"] == 2
    assert final["compile_cache"]["mesh_devices"] == 4
    assert final["compile_cache"]["forest_precision"] == "int8"

"""End-to-end train()/predict(): quality ladder vs linear + sklearn oracle.

The reference validates by a monotone quality ladder (glmnet 0.146 < GBDT
0.0957 < tuned ensemble 0.0944 — SURVEY.md §4 item 4); here the same ladder
runs on synthetic data with sklearn models as independent oracles.
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


def rmse(y, p):
    return float(np.sqrt(np.mean((y - p) ** 2)))


@pytest.fixture(scope="module")
def reg_split(rng=None):
    rng = np.random.default_rng(7)
    n = 4000
    X = rng.normal(0, 1, (n, 6))
    y = (2.0 * X[:, 0] + np.sin(3 * X[:, 1]) + X[:, 2] * (X[:, 3] > 0)
         + 0.1 * rng.normal(0, 1, n))
    return (X[:3000], y[:3000], X[3000:], y[3000:])


def test_beats_linear_model(reg_split):
    Xtr, ytr, Xte, yte = reg_split
    from sklearn.linear_model import LinearRegression

    lin = LinearRegression().fit(Xtr, ytr)
    lin_rmse = rmse(yte, lin.predict(Xte))

    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train({"objective": "regression", "learning_rate": 0.1,
                         "verbosity": 0}, dtrain, num_boost_round=100)
    gbdt_rmse = rmse(yte, booster.predict(Xte))
    assert gbdt_rmse < lin_rmse * 0.7, (gbdt_rmse, lin_rmse)


def test_close_to_sklearn_hist_gbdt(reg_split):
    Xtr, ytr, Xte, yte = reg_split
    from sklearn.ensemble import HistGradientBoostingRegressor

    sk = HistGradientBoostingRegressor(
        max_iter=100, learning_rate=0.1, max_leaf_nodes=31,
        min_samples_leaf=20, early_stopping=False).fit(Xtr, ytr)
    sk_rmse = rmse(yte, sk.predict(Xte))

    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train({"objective": "regression", "learning_rate": 0.1,
                         "num_leaves": 31, "min_data_in_leaf": 20,
                         "verbosity": 0}, dtrain, num_boost_round=100)
    our_rmse = rmse(yte, booster.predict(Xte))
    # independent oracle: same config class should land within 15%
    assert our_rmse < sk_rmse * 1.15, (our_rmse, sk_rmse)


def test_training_loss_decreases(reg_split):
    Xtr, ytr, _, _ = reg_split
    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train({"objective": "regression", "verbosity": 0},
                        dtrain, num_boost_round=50)
    p10 = booster.predict(Xtr, num_iteration=10)
    p50 = booster.predict(Xtr, num_iteration=50)
    assert rmse(ytr, p50) < rmse(ytr, p10)


def test_staged_prediction_prefix_consistency(reg_split):
    # xgboost ntree_limit contract (bagging_boosting.ipynb:136)
    Xtr, ytr, Xte, _ = reg_split
    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train({"objective": "regression", "verbosity": 0},
                        dtrain, num_boost_round=30)
    full = booster.predict(Xte, num_iteration=30)
    alias = booster.predict(Xte, ntree_limit=30)
    np.testing.assert_allclose(full, alias, rtol=1e-6)
    p1 = booster.predict(Xte, num_iteration=1)
    p29 = booster.predict(Xte, num_iteration=29)
    assert not np.allclose(p1, full)
    assert np.abs(p29 - full).max() < np.abs(p1 - full).max()


def test_early_stopping_with_valid_set(reg_split):
    Xtr, ytr, Xte, yte = reg_split
    dtrain = lgb.Dataset(Xtr, label=ytr)
    dvalid = lgb.Dataset(Xte, label=yte, reference=dtrain)
    booster = lgb.train(
        {"objective": "regression", "learning_rate": 0.3, "verbosity": 0,
         "metric": "rmse"},
        dtrain, num_boost_round=500, valid_sets=[dvalid],
        early_stopping_rounds=5)
    assert 0 < booster.best_iteration <= 500
    assert "valid_0" in booster.best_score
    assert "rmse" in booster.best_score["valid_0"]


def test_binary_objective_auc(small_binary_module=None):
    rng = np.random.default_rng(11)
    n = 3000
    X = rng.normal(0, 1, (n, 5))
    logits = 1.5 * X[:, 0] - X[:, 1] + X[:, 2] * X[:, 3]
    y = (rng.random(n) < 1 / (1 + np.exp(-logits))).astype(np.float64)
    dtrain = lgb.Dataset(X[:2400], label=y[:2400])
    booster = lgb.train({"objective": "binary", "verbosity": 0},
                        dtrain, num_boost_round=60)
    p = booster.predict(X[2400:])
    assert p.min() >= 0 and p.max() <= 1
    from sklearn.metrics import roc_auc_score

    auc = roc_auc_score(y[2400:], p)
    assert auc > 0.85, auc


def test_bagging_and_feature_fraction_run(reg_split):
    Xtr, ytr, Xte, yte = reg_split
    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train(
        {"objective": "regression", "bagging_fraction": 0.6,
         "bagging_freq": 4, "feature_fraction": 0.8, "verbosity": 0},
        dtrain, num_boost_round=60)
    assert rmse(yte, booster.predict(Xte)) < rmse(yte, np.full(len(yte), ytr.mean()))


def test_deterministic_same_seed(reg_split):
    Xtr, ytr, Xte, _ = reg_split
    params = {"objective": "regression", "bagging_fraction": 0.7,
              "bagging_freq": 2, "feature_fraction": 0.8, "seed": 5,
              "verbosity": 0}
    b1 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
    b2 = lgb.train(params, lgb.Dataset(Xtr, label=ytr), num_boost_round=20)
    np.testing.assert_allclose(b1.predict(Xte), b2.predict(Xte), rtol=1e-6)


def test_sample_weights_shift_fit():
    rng = np.random.default_rng(13)
    n = 2000
    X = rng.normal(0, 1, (n, 2))
    y = np.where(X[:, 0] > 0, 1.0, -1.0)
    w = np.where(X[:, 0] > 0, 10.0, 0.1)
    dtrain = lgb.Dataset(X, label=y, weight=w)
    booster = lgb.train({"objective": "regression", "num_leaves": 2,
                         "verbosity": 0, "min_data_in_leaf": 1},
                        dtrain, num_boost_round=1)
    # with extreme weights, init score (weighted mean) leans to +1
    assert booster.init_score_ > 0.5


def test_save_load_roundtrip(tmp_path, reg_split):
    Xtr, ytr, Xte, _ = reg_split
    dtrain = lgb.Dataset(Xtr, label=ytr)
    booster = lgb.train({"objective": "regression", "verbosity": 0},
                        dtrain, num_boost_round=15)
    path = str(tmp_path / "model.json")
    booster.save_model(path)
    loaded = lgb.Booster(model_file=path)
    np.testing.assert_allclose(booster.predict(Xte), loaded.predict(Xte),
                               rtol=1e-6)

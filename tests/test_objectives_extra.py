"""mape / gamma / tweedie / cross_entropy objectives + metrics.

Oracle strategy (SURVEY.md §4): each objective must beat predicting the
optimal CONSTANT under its own loss, and link functions must produce valid
outputs (positive for the log-link families, [0,1] for cross-entropy).
"""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def pos_data():
    rng = np.random.default_rng(8)
    n = 3000
    X = rng.normal(size=(n, 4)).astype(np.float32)
    mu = np.exp(0.8 * X[:, 0] - 0.5 * X[:, 1] + 0.2)
    y = rng.gamma(shape=2.0, scale=mu / 2.0).astype(np.float32) + 1e-3
    return X, y


def _const_loss(y, loss):
    from scipy.optimize import minimize_scalar

    r = minimize_scalar(lambda c: float(loss(np.full_like(y, c), y)),
                        bounds=(float(y.min()), float(y.max())),
                        method="bounded")
    return float(r.fun)


def test_gamma_objective(pos_data):
    X, y = pos_data
    b = lgb.train({"objective": "gamma", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=40)
    mu = b.predict(X)
    assert np.all(mu > 0)

    def nll(pred, yy):
        return np.mean(np.log(pred) + yy / pred)

    assert nll(mu, y) < _const_loss(y, nll) - 0.05


def test_tweedie_objective(pos_data):
    X, y = pos_data
    b = lgb.train({"objective": "tweedie", "tweedie_variance_power": 1.3,
                   "verbosity": -1}, lgb.Dataset(X, label=y),
                  num_boost_round=40)
    mu = b.predict(X)
    assert np.all(mu > 0)
    rho = 1.3

    def dev(pred, yy):
        return np.mean(-yy * pred ** (1 - rho) / (1 - rho)
                       + pred ** (2 - rho) / (2 - rho))

    assert dev(mu, y) < _const_loss(y, dev) - 1e-3
    # metric name resolves and appears in eval history; the user's rho
    # reaches the fused-cv metric (code-review r2: it silently used 1.5)
    res13 = lgb.cv({"objective": "tweedie", "verbosity": -1,
                    "tweedie_variance_power": 1.3},
                   lgb.Dataset(X, label=y), num_boost_round=5, nfold=3,
                   seed=3)
    res19 = lgb.cv({"objective": "tweedie", "verbosity": -1,
                    "tweedie_variance_power": 1.9},
                   lgb.Dataset(X, label=y), num_boost_round=5, nfold=3,
                   seed=3)
    key = "valid tweedie-mean"
    assert key in res13
    assert not np.allclose(res13[key], res19[key])


def test_mape_objective():
    rng = np.random.default_rng(1)
    n = 3000
    X = rng.normal(size=(n, 3)).astype(np.float32)
    y = (10.0 * np.exp(X[:, 0]) + rng.normal(0, 1.0, n)).astype(np.float32)
    b = lgb.train({"objective": "mape", "verbosity": -1,
                   "metric": "mape"}, lgb.Dataset(X, label=y),
                  num_boost_round=60)
    pred = b.predict(X)

    def mape(p, yy):
        return np.mean(np.abs(p - yy) / np.maximum(np.abs(yy), 1.0))

    assert mape(pred, y) < mape(np.full_like(y, np.median(y)), y) * 0.7


def test_cross_entropy_continuous_labels():
    rng = np.random.default_rng(2)
    n = 3000
    X = rng.normal(size=(n, 3)).astype(np.float32)
    p_true = 1.0 / (1.0 + np.exp(-(1.5 * X[:, 0] - X[:, 1])))
    # labels are PROBABILITIES, not 0/1 — the xentropy contract
    y = p_true.astype(np.float32)
    b = lgb.train({"objective": "xentropy", "verbosity": -1},
                  lgb.Dataset(X, label=y), num_boost_round=40)
    p = b.predict(X)
    assert np.all((p > 0) & (p < 1))
    assert float(np.mean(np.abs(p - p_true))) < 0.05


def test_objective_aliases_resolve():
    from lightgbm_tpu.config import parse_params

    assert parse_params({"objective": "xentropy"}).objective == \
        "cross_entropy"
    assert parse_params(
        {"objective": "mean_absolute_percentage_error"}).objective == "mape"
    p = parse_params({"objective": "tweedie",
                      "tweedie_variance_power": 1.7})
    assert p.tweedie_variance_power == 1.7

"""RData (RDX2) ledger compatibility (SURVEY.md §7 paramGrid.RData compat)."""

import os

import numpy as np
import pytest

from lightgbm_tpu.utils.rdata import read_rdata, write_rdata
from lightgbm_tpu.utils.sweep import SweepLedger, expand_grid

REF = "/root/reference/paramGrid.RData"


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
def test_read_reference_artifact():
    """Parse the reference's actual sweep checkpoint: 108x9 data.frame,
    80 completed rows, 28 crashed lr=0.01 sentinels (SURVEY.md §2A row 5)."""
    d = read_rdata(REF)
    pg = d["paramGrid"]
    assert list(pg.keys()) == [
        "iteration", "score", "learning_rate", "num_leaves",
        "min_data_in_leaf", "feature_fraction", "bagging_fraction",
        "bagging_freq", "nthread"]
    sc = np.asarray(pg["score"], dtype=float)
    assert len(sc) == 108
    done = sc != -1
    assert done.sum() == 80
    assert np.all(np.asarray(pg["learning_rate"], float)[~done] == 0.01)
    assert abs(sc[done].max() - -0.0092703) < 1e-6


def test_write_read_roundtrip(tmp_path):
    cols = {"iteration": [269, -1], "score": [-0.0095, -1.0],
            "name": ["a", None], "flag": [True, False]}
    p = str(tmp_path / "t.RData")
    write_rdata(p, "paramGrid", cols)
    out = read_rdata(p)["paramGrid"]
    assert out["iteration"] == [269, -1]
    assert out["score"] == [-0.0095, -1.0]
    assert out["name"] == ["a", None]
    assert out["flag"] == [1, 0]  # R logicals read back as ints


def test_ledger_rdata_checkpoint_resume(tmp_path):
    """SweepLedger with an .RData path writes R-loadable checkpoints and
    resumes from them (the r/gridsearchCV.R:118,121 save/load pattern)."""
    grid = expand_grid(learning_rate=[0.1, 0.01], num_leaves=[31, 63],
                       nthread=[4])
    path = str(tmp_path / "paramGrid.RData")
    led = SweepLedger(grid, path)
    led.record(0, 100, -0.5)
    led.record(2, 200, -0.25)

    led2 = SweepLedger(grid, path)
    assert led2.done(0) and led2.done(2)
    assert not led2.done(1) and not led2.done(3)
    assert led2.rows[2]["iteration"] == 200
    assert led2.rows[2]["score"] == -0.25


@pytest.mark.skipif(not os.path.exists(REF), reason="reference not mounted")
def test_ledger_resumes_from_reference_checkpoint():
    """The TPU sweep can resume the reference's OWN crashed checkpoint:
    the 80 completed rows are skipped, the 28 lr=0.01 sentinels rerun."""
    grid = expand_grid(
        learning_rate=[0.1, 0.05, 0.01],
        num_leaves=[31, 63, 127],
        min_data_in_leaf=[20, 40],
        feature_fraction=[0.8, 1.0],
        bagging_fraction=[0.6, 0.8, 1.0],
        bagging_freq=[4],
        nthread=[4],
    )
    assert len(grid) == 108
    led = SweepLedger(grid, REF)
    n_done = sum(led.done(i) for i in range(108))
    assert n_done == 80
    for i in range(108):
        if not led.done(i):
            assert led.rows[i]["learning_rate"] == 0.01

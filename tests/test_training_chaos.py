"""Training chaos tests (ISSUE r13 tentpole c+d + satellites).

Deterministic fault injection against the TRAINING stack: transient
block-read/transfer faults absorbed by the bounded retry with ZERO
effect on the trained forest, integrity failures quarantined with the
block index attached, poisoned gradients stopped by the finiteness
screen instead of growing garbage trees, and checkpoint-write faults
that cost a generation but never the run.  Plus the shared-registry
backward-compat surface and the ``Booster(model_file=...)`` continued-
training path (satellites 1-3).
"""

import os
import warnings

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.data import OOCBlockError
from lightgbm_tpu.dataset import Dataset
from lightgbm_tpu.faults import (
    SERVING_SITES,
    SITES,
    TRAINING_SITES,
    FaultError,
    FaultInjector,
    FaultSpec,
    NonFiniteGradientError,
)
from lightgbm_tpu.training import (
    latest_checkpoint,
    list_checkpoints,
    load_checkpoint,
    train_resumable,
)


def _problem(n=700, f=5, seed=0):
    rng = np.random.default_rng(seed)
    X = rng.normal(0, 1, (n, f)).astype(np.float32)
    w = rng.normal(0, 1, f)
    y = (rng.random(n) < 1 / (1 + np.exp(-(X @ w)))).astype(np.float32)
    return X, y


def _trees_equal(a, b):
    if len(a.trees) != len(b.trees):
        return False
    for ta, tb in zip(a.trees, b.trees):
        for field in ("split_feature", "split_bin", "left", "right",
                      "leaf_value", "is_leaf"):
            if not np.array_equal(np.asarray(getattr(ta, field)),
                                  np.asarray(getattr(tb, field))):
                return False
    return True


def _streamed(block_rows=256, seed=0, **extra):
    """A constructed streamed Booster + its BlockStore, retry sleep
    pinned to a no-op so the chaos tests don't wall-clock wait."""
    X, y = _problem(seed=seed)
    p = dict(objective="binary", num_leaves=7, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7,
             stream_block_rows=block_rows, **extra)
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, len(X), block_rows)]
    ds = Dataset.from_blocks(blocks, params=dict(p))
    b = lgb.Booster(p, ds)
    store = ds.block_store
    store._sleep = lambda s: None
    return b, store


# -- shared fault registry (satellite 1) ---------------------------------


def test_shared_registry_and_serving_backward_compat():
    assert set(TRAINING_SITES) == {"block_read", "device_put",
                                   "checkpoint_write", "gradient"}
    from lightgbm_tpu.faults import PIPELINE_SITES, SWEEP_SITES
    assert SITES == (SERVING_SITES + TRAINING_SITES + PIPELINE_SITES
                     + SWEEP_SITES)
    # the serving shim must re-export the SAME objects, training sites
    # included, so existing serving chaos code keeps working unchanged
    from lightgbm_tpu.serving import faults as sfaults
    assert sfaults.FaultInjector is FaultInjector
    assert sfaults.FaultError is FaultError
    assert sfaults.FaultSpec is FaultSpec
    assert sfaults.SITES == SITES
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultSpec("no_such_site")
    with pytest.raises(ValueError, match="unknown fault site"):
        FaultInjector().check("no_such_site")


def test_training_sites_count_hits_deterministically():
    inj = FaultInjector([FaultSpec("block_read", after=1, times=1)])
    inj.check("block_read")                       # hit 1: clean
    with pytest.raises(FaultError):
        inj.check("block_read")                   # hit 2: fires
    inj.check("block_read")                       # hit 3: spent
    snap = inj.snapshot()
    assert snap["hits"]["block_read"] == 3
    assert snap["fired"]["block_read"] == 1


# -- streaming-path hardening (tentpole c) -------------------------------


def test_transient_block_read_fault_absorbed_bit_identical():
    clean, _ = _streamed()
    for _ in range(2):
        clean.update()

    b, store = _streamed()
    store.fault_injector = FaultInjector(
        [FaultSpec("block_read", times=2, message="transient host read")])
    for _ in range(2):
        b.update()
    assert store.read_retries >= 2          # both firings were absorbed
    assert store.fault_injector.fired["block_read"] == 2
    assert not store.quarantined
    assert _trees_equal(clean, b)           # zero effect on the forest
    assert np.array_equal(np.asarray(clean._pred_train),
                          np.asarray(b._pred_train))


def test_transient_device_put_fault_absorbed():
    clean, _ = _streamed()
    clean.update()
    b, store = _streamed()
    store.fault_injector = FaultInjector([FaultSpec("device_put", times=1)])
    b.update()
    assert store.read_retries == 1
    assert _trees_equal(clean, b)


def test_persistent_read_fault_exhausts_retry_with_block_context():
    b, store = _streamed()
    store.fault_injector = FaultInjector(
        [FaultSpec("block_read", times=-1, message="host gone")])
    with pytest.raises(OOCBlockError) as ei:
        b.update()
    e = ei.value
    assert e.kind == "read"
    assert e.block == 0
    assert e.attempts == store.max_read_retries + 1
    assert isinstance(e.__cause__, FaultError)   # upstream cause chained
    assert "host gone" in str(e.__cause__)


def test_corrupt_block_quarantined_no_retry():
    b, store = _streamed()
    store.blocks[1][0, 0] ^= 1              # host-side bit flip
    with pytest.raises(OOCBlockError) as ei:
        b.update()
    assert ei.value.kind == "corrupt"
    assert ei.value.block == 1
    assert 1 in store.quarantined
    assert store.read_retries == 0          # integrity failures never retry


def test_short_block_quarantined():
    b, store = _streamed()
    store.blocks[2] = store.blocks[2][:128]  # lost rows after construction
    with pytest.raises(OOCBlockError) as ei:
        b.update()
    assert ei.value.kind == "short"
    assert ei.value.block == 2
    assert 2 in store.quarantined


def test_nonfinite_predictions_screened_before_growing():
    b, _ = _streamed()
    b.update()
    import jax.numpy as jnp
    b._pred_train = b._pred_train.at[3].set(jnp.nan)
    with pytest.raises(NonFiniteGradientError) as ei:
        b.update()
    assert ei.value.round_index == 1
    assert b.num_trees() == 1               # no garbage tree was grown


# -- resumable loop under injected faults (tentpole d) -------------------


def test_gradient_poison_stops_run_and_prior_checkpoint_resumes(tmp_path):
    X, y = _problem()
    p = dict(objective="binary", num_leaves=7, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7)
    def make_ds():
        return Dataset(X, label=y, params=dict(p))
    ref = lgb.Booster(dict(p), make_ds())
    for _ in range(4):
        ref.update()

    d = str(tmp_path / "ckpts")
    inj = FaultInjector([FaultSpec("gradient", after=2, times=1,
                                   message="upstream corruption")])
    with pytest.raises(NonFiniteGradientError) as ei:
        train_resumable(dict(p), make_ds(), 4, checkpoint_dir=d,
                        checkpoint_rounds=1, keep_last=8, resume=False,
                        injector=inj)
    assert ei.value.round_index == 2        # rounds 0,1 clean, 2 poisoned
    assert load_checkpoint(latest_checkpoint(d))[1]["iter"] == 2

    # the last checkpoint PRECEDES the corruption: resuming it and
    # rerunning the lost rounds reproduces the uninterrupted forest
    res = train_resumable(dict(p), make_ds(), 4, checkpoint_dir=d,
                          checkpoint_rounds=1, resume=True)
    assert res.completed
    assert _trees_equal(ref, res.booster)


def test_checkpoint_write_fault_costs_generation_not_run(tmp_path):
    X, y = _problem()
    p = dict(objective="binary", num_leaves=7, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7)
    def make_ds():
        return Dataset(X, label=y, params=dict(p))
    ref = lgb.Booster(dict(p), make_ds())
    for _ in range(4):
        ref.update()

    d = str(tmp_path / "ckpts")
    inj = FaultInjector([FaultSpec("checkpoint_write", after=1, times=1)])
    with pytest.warns(UserWarning, match="checkpoint write failed"):
        res = train_resumable(dict(p), make_ds(), 4, checkpoint_dir=d,
                              checkpoint_rounds=1, keep_last=8,
                              resume=False, injector=inj)
    assert res.completed
    assert res.checkpoint_failures == 1
    assert _trees_equal(ref, res.booster)   # training never flinched
    # the fault hit iter 2's write; every other generation landed, no
    # torn tmp file survived, and the prior checkpoint stayed loadable
    iters = [load_checkpoint(q)[1]["iter"] for q in list_checkpoints(d)]
    assert iters == [1, 3, 4]
    assert not [n for n in os.listdir(d) if n.startswith(".tmp-")]


def test_streamed_resume_with_transient_faults_bit_identical(tmp_path):
    """Kitchen sink: streamed multi-block + bagging, a transient read
    fault on the first run, a resume on the second — forest still equals
    the uninterrupted run's."""
    block_rows = 256
    X, y = _problem()
    p = dict(objective="binary", num_leaves=7, learning_rate=0.2,
             max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7,
             bagging_fraction=0.8, bagging_freq=1,
             stream_block_rows=block_rows)
    blocks = [(X[lo:lo + block_rows], y[lo:lo + block_rows])
              for lo in range(0, len(X), block_rows)]
    def make_ds():
        return Dataset.from_blocks(blocks, params=dict(p))
    ref = lgb.Booster(dict(p), make_ds())
    for _ in range(4):
        ref.update()

    d = str(tmp_path / "ckpts")
    ds1 = make_ds()
    ds1.block_store._sleep = lambda s: None
    ds1.block_store.fault_injector = FaultInjector(
        [FaultSpec("block_read", after=3, times=1)])
    res = train_resumable(dict(p), ds1, 2, checkpoint_dir=d,
                          checkpoint_rounds=1, resume=False)
    assert res.completed and ds1.block_store.read_retries >= 0

    res2 = train_resumable(dict(p), make_ds(), 4, checkpoint_dir=d,
                           checkpoint_rounds=1, resume=True)
    assert res2.completed and res2.resumed_from is not None
    assert _trees_equal(ref, res2.booster)
    assert np.array_equal(np.asarray(ref._pred_train),
                          np.asarray(res2.booster._pred_train))


# -- model-file continued training (satellite 2) -------------------------


def _cont_params():
    return dict(objective="binary", num_leaves=7, learning_rate=0.2,
                max_bin=31, min_data_in_leaf=5, verbose=-1, seed=7)


def test_model_file_continuation_bit_identical(tmp_path):
    X, y = _problem()
    p = _cont_params()
    ref = lgb.Booster(dict(p), Dataset(X, label=y, params=dict(p)))
    for _ in range(5):
        ref.update()

    b1 = lgb.Booster(dict(p), Dataset(X, label=y, params=dict(p)))
    for _ in range(3):
        b1.update()
    path = str(tmp_path / "model.json")
    b1.save_model(path)

    b2 = lgb.Booster(model_file=path)
    ds2 = Dataset(X, label=y, params=dict(p))
    for _ in range(2):
        b2.update(train_set=ds2)
    assert b2.num_trees() == 5
    assert _trees_equal(ref, b2)
    assert np.array_equal(ref.predict(X), b2.predict(X))


def test_model_file_continuation_rejects_different_binning(tmp_path):
    X, y = _problem()
    p = _cont_params()
    b1 = lgb.Booster(dict(p), Dataset(X, label=y, params=dict(p)))
    b1.update()
    path = str(tmp_path / "model.json")
    b1.save_model(path)

    b2 = lgb.Booster(model_file=path)
    X2, y2 = _problem(seed=99)
    with pytest.raises(ValueError, match="binning"):
        b2.update(train_set=Dataset(X2 * 3.0 + 1.0, label=y2,
                                    params=dict(p)))


def test_model_file_continuation_streamed_bit_identical(tmp_path):
    """r15: the streamed-continuation fence is lifted — continuing a
    saved model on a ``from_blocks`` Dataset replays the loaded forest
    through the block loop and matches the uninterrupted run exactly."""
    X, y = _problem()
    ps = dict(_cont_params(), stream_block_rows=256)
    blocks = [(X[lo:lo + 256], y[lo:lo + 256])
              for lo in range(0, len(X), 256)]

    def ds():
        return Dataset.from_blocks(blocks, params=dict(ps))

    ref = lgb.train(dict(ps), ds(), num_boost_round=5)
    base = lgb.train(dict(ps), ds(), num_boost_round=3)
    path = str(tmp_path / "model.json")
    base.save_model(path)

    b2 = lgb.Booster(model_file=path)
    ds2 = ds()
    for _ in range(2):
        b2.update(train_set=ds2)
        ds2 = None
    assert b2.num_trees() == 5
    assert _trees_equal(ref, b2)


# -- checkpoint-overhead budget (satellite 5) ----------------------------


def test_ckpt_overhead_budgets_green():
    from lightgbm_tpu.analysis.budgets import (CKPT_BUDGETS,
                                               check_ckpt_budgets,
                                               ckpt_overhead_time)
    res = check_ckpt_budgets()
    assert res and all(r["ok"] for r in res)
    names = [r["name"] for r in res]
    assert "ckpt_overhead_ref" in names
    # the reference shape holds the <=5% bar with the default cadence
    t = ckpt_overhead_time()
    assert t["overhead_frac"] <= 0.05
    # ... and the guard-the-model entry shows every-round checkpointing
    # at small-shard scale genuinely violates it (cmp="ge")
    uneco = [b for b in CKPT_BUDGETS
             if b.name == "ckpt_every_round_uneconomic"][0]
    assert uneco.cmp == "ge" and uneco.check()["ok"]


def test_schema_digest_distinguishes_binnings():
    from lightgbm_tpu.data import schema_digest
    X, y = _problem()
    d1 = Dataset(X, label=y)
    d1.construct()
    d1b = Dataset(X.copy(), label=y.copy())
    d1b.construct()
    d2 = Dataset(X * 3.0 + 1.0, label=y)
    d2.construct()
    a = schema_digest(d1.bin_mapper)
    assert a == schema_digest(d1b.bin_mapper)    # deterministic
    assert a != schema_digest(d2.bin_mapper)     # drift detected

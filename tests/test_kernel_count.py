"""Kernel-count regression guard (r7 satellite).

PERF.md's r4/r5 analysis showed the training floor is kernel LAUNCH
count (~1,500/round in the fused-CV sweep at ~9 us each), so op-count
regressions must fail tier-1 instead of surfacing rounds later in a
bench.  One strict split iteration and one fused-CV-shaped round are
lowered to compiled HLO on CPU and the growth-loop body's
fusion/custom-call counts asserted against checked-in budgets
(measured value + ~25% headroom; see tools/hlo_counts.py for what each
view means).
"""

import pytest

from tools.hlo_counts import split_iter_counts

# measured on the r7 jax pin: strict (23 unfused / 45 fused-inlined /
# 5+1 stub), E-batched (21 / 53 / 5+1).  Budgets leave ~25% headroom.
BUDGET = {
    "strict_unfused": 29,
    "strict_fused_cpu": 56,
    "strict_tpu_model": 8,
    "cv_unfused": 27,
    "cv_fused_cpu": 66,
    "cv_tpu_model": 8,
}


def total(counts):
    return counts[0] + counts[1]


def test_strict_split_iteration_budgets():
    assert total(split_iter_counts(False)) <= BUDGET["strict_unfused"]
    assert total(split_iter_counts(True)) <= BUDGET["strict_fused_cpu"]
    model = total(split_iter_counts(True, stub=True))
    assert model <= BUDGET["strict_tpu_model"]


def test_fused_cv_round_budgets():
    # E=8 compiles ~5x faster than the production E=40 bucket and has
    # IDENTICAL per-iteration body counts (vmapped ops don't multiply
    # with batch size) — verified against E=40 when the budget was set.
    e = 8
    assert total(split_iter_counts(False, e=e)) <= BUDGET["cv_unfused"]
    assert total(split_iter_counts(True, e=e)) <= BUDGET["cv_fused_cpu"]
    model = total(split_iter_counts(True, e=e, stub=True))
    assert model <= BUDGET["cv_tpu_model"]
    # the r7 tentpole claim: >= 3x launch-count drop per split iteration
    # vs the r4 TPU-measured baseline (49 fusions + 1 custom-call)
    assert model * 3 <= 50

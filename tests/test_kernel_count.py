"""Kernel-count regression guard (r7 satellite; declarative since r8).

PERF.md's r4/r5 analysis showed the training floor is kernel LAUNCH
count (~1,500/round in the fused-CV sweep at ~9 us each), so op-count
regressions must fail tier-1 instead of surfacing rounds later in a
bench.  The budgets themselves are DECLARATIVE specs in
``lightgbm_tpu.analysis.budgets.LAUNCH_BUDGETS`` (one model shared with
``python -m lightgbm_tpu lint --budgets`` and the bench artifacts); this
file is a thin consumer that lowers each spec's entry point and asserts
``measured <= budget``.
"""

import pytest

from lightgbm_tpu.analysis.budgets import LAUNCH_BUDGETS, budget_by_name


@pytest.mark.lint
@pytest.mark.parametrize("spec", LAUNCH_BUDGETS, ids=lambda s: s.name)
def test_launch_budget(spec):
    result = spec.check()
    assert result["ok"], (
        f"{spec.name}: measured {result['measured']} launches > budget "
        f"{spec.budget} ({spec.note})")


@pytest.mark.lint
def test_r7_tentpole_margin():
    # the r7 tentpole claim: >= 3x launch-count drop per split iteration
    # vs the r4 TPU-measured baseline (49 fusions + 1 custom-call)
    model = budget_by_name("cv_tpu_model").measure()
    assert model * 3 <= 50

"""Best-first tree grower: exact fits, leaf budgets, constraints."""

import numpy as np
import pytest

import jax.numpy as jnp

from lightgbm_tpu.models.tree import grow_tree
from lightgbm_tpu.ops.predict import predict_tree_binned
from lightgbm_tpu.ops.split import SplitContext


def make_ctx(l1=0.0, l2=0.0, min_data=1.0, min_hess=0.0, min_gain=0.0):
    return SplitContext(
        lambda_l1=jnp.float32(l1), lambda_l2=jnp.float32(l2),
        min_data_in_leaf=jnp.float32(min_data),
        min_sum_hessian=jnp.float32(min_hess),
        min_gain_to_split=jnp.float32(min_gain))


def grow_simple(bins, residual, num_leaves, num_bins, max_depth=-1,
                min_data=1.0):
    """L2 stump fit: grad = pred - y with pred=0 -> grad = -residual, hess=1."""
    n = bins.shape[0]
    stats = jnp.stack([jnp.asarray(-residual, jnp.float32),
                       jnp.ones(n, jnp.float32),
                       jnp.ones(n, jnp.float32)], axis=-1)
    fmask = jnp.ones(bins.shape[1], jnp.float32)
    return grow_tree(jnp.asarray(bins), stats, fmask,
                     make_ctx(min_data=min_data), num_leaves, num_bins,
                     max_depth)


def test_single_split_recovers_step_function():
    # y = 1 for bin >= 2, else 0; one split at bin 1 fits exactly
    bins = np.repeat(np.arange(4, dtype=np.uint8), 25).reshape(-1, 1)
    y = (bins[:, 0] >= 2).astype(np.float32)
    tree, row_leaf = grow_simple(bins, y, num_leaves=2, num_bins=4)
    assert int(tree.num_leaves) == 2
    pred = np.asarray(tree.leaf_value)[np.asarray(row_leaf)]
    np.testing.assert_allclose(pred, y, atol=1e-5)
    assert int(tree.split_feature[0]) == 0
    assert int(tree.split_bin[0]) == 1


def test_full_tree_fits_piecewise_constant():
    # 4 distinct levels need 4 leaves to fit exactly
    bins = np.repeat(np.arange(4, dtype=np.uint8), 30).reshape(-1, 1)
    y = np.array([0.0, 5.0, -2.0, 3.0], np.float32)[bins[:, 0]]
    tree, row_leaf = grow_simple(bins, y, num_leaves=4, num_bins=4)
    assert int(tree.num_leaves) == 4
    pred = np.asarray(tree.leaf_value)[np.asarray(row_leaf)]
    np.testing.assert_allclose(pred, y, atol=1e-5)


def test_leaf_budget_respected():
    rng = np.random.default_rng(0)
    bins = rng.integers(0, 16, (500, 3)).astype(np.uint8)
    y = rng.normal(0, 1, 500).astype(np.float32)
    tree, _ = grow_simple(bins, y, num_leaves=7, num_bins=16)
    assert int(tree.num_leaves) <= 7
    assert int(np.asarray(tree.is_leaf).sum()) == int(tree.num_leaves)


def test_best_first_order_takes_biggest_gain_first():
    # feature 0 separates a huge residual group; feature 1 a small one.
    # With num_leaves=2 only the big split must be made.
    n = 400
    bins = np.zeros((n, 2), np.uint8)
    bins[:200, 0] = 1
    bins[::2, 1] = 1
    y = np.where(np.arange(n) < 200, 10.0, -10.0).astype(np.float32)
    y += np.where(np.arange(n) % 2 == 0, 0.5, -0.5)
    tree, _ = grow_simple(bins, y, num_leaves=2, num_bins=2)
    assert int(tree.split_feature[0]) == 0


def test_max_depth_limits_growth():
    rng = np.random.default_rng(1)
    bins = rng.integers(0, 32, (1000, 2)).astype(np.uint8)
    y = rng.normal(0, 1, 1000).astype(np.float32)
    tree, _ = grow_simple(bins, y, num_leaves=31, num_bins=32)
    tree_d2, _ = grow_simple(bins, y, num_leaves=31, num_bins=32)
    n = bins.shape[0]
    stats = jnp.stack([jnp.asarray(-y), jnp.ones(n), jnp.ones(n)], axis=-1)
    tree_d2, _ = grow_tree(jnp.asarray(bins), stats, jnp.ones(2),
                           make_ctx(), 31, 32, max_depth=2)
    # depth<=2 allows at most 4 leaves
    assert int(tree_d2.num_leaves) <= 4
    assert int(tree.num_leaves) > int(tree_d2.num_leaves)


def test_min_data_in_leaf_respected():
    rng = np.random.default_rng(2)
    bins = rng.integers(0, 8, (300, 2)).astype(np.uint8)
    y = rng.normal(0, 1, 300).astype(np.float32)
    tree, row_leaf = grow_simple(bins, y, num_leaves=16, num_bins=8,
                                 min_data=50.0)
    leaves = np.asarray(row_leaf)
    is_leaf = np.asarray(tree.is_leaf)
    for node in np.unique(leaves):
        assert is_leaf[node]
        assert (leaves == node).sum() >= 50


def test_traversal_matches_training_assignment():
    rng = np.random.default_rng(3)
    bins = rng.integers(0, 16, (600, 4)).astype(np.uint8)
    y = (bins[:, 0] * 1.0 + (bins[:, 1] > 8) * 5.0).astype(np.float32)
    tree, row_leaf = grow_simple(bins, y, num_leaves=15, num_bins=16)
    vals_train = np.asarray(tree.leaf_value)[np.asarray(row_leaf)]
    vals_traverse = np.asarray(
        predict_tree_binned(tree, jnp.asarray(bins), max_depth_cap=15))
    np.testing.assert_allclose(vals_train, vals_traverse, atol=1e-6)


def test_pure_leaf_stops_splitting():
    bins = np.zeros((100, 1), np.uint8)  # single bin: nothing to split
    y = np.ones(100, np.float32)
    tree, _ = grow_simple(bins, y, num_leaves=8, num_bins=4)
    assert int(tree.num_leaves) == 1
    np.testing.assert_allclose(float(tree.leaf_value[0]), 1.0, atol=1e-5)

"""Exclusive Feature Bundling (VERDICT r1 item 7; SURVEY.md §2C EFB row)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb
from lightgbm_tpu.dataset import FeatureBundler


@pytest.fixture(scope="module")
def onehot_data():
    """200 one-hot columns from a 200-category variable + 3 dense features:
    the one-hots are perfectly mutually exclusive -> EFB's home turf."""
    rng = np.random.default_rng(5)
    n, k = 6000, 200
    cat = rng.integers(0, k, n)
    onehot = np.zeros((n, k), np.float32)
    onehot[np.arange(n), cat] = 1.0
    dense = rng.normal(size=(n, 3)).astype(np.float32)
    X = np.concatenate([dense, onehot], axis=1)
    effect = rng.normal(0, 1.0, k)
    y = (dense[:, 0] + effect[cat] + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def test_bundles_collapse_onehot_columns(onehot_data):
    X, y = onehot_data
    ds = lgb.Dataset(X, label=y)
    ds.construct()
    bundler = ds.bin_mapper.bundler
    assert bundler is not None, "mutually exclusive one-hots must bundle"
    # 200 one-hot features collapse into very few bundle columns
    assert ds.num_feature_ < 20, ds.num_feature_
    assert ds.num_feature() == X.shape[1]  # user-facing count unchanged
    # every original feature appears in exactly one group
    members = sorted(f for g in bundler.groups for f in g)
    assert members == list(range(X.shape[1]))


def test_bundled_training_matches_unbundled_quality(onehot_data):
    X, y = onehot_data
    params = {"objective": "regression", "num_leaves": 63,
              "learning_rate": 0.1, "verbosity": -1, "min_data_in_leaf": 5}
    b_on = lgb.train(dict(params), lgb.Dataset(X, label=y),
                     num_boost_round=60)
    b_off = lgb.train(dict(params, enable_bundle=False),
                      lgb.Dataset(X, label=y), num_boost_round=60)
    r_on = float(np.sqrt(np.mean((b_on.predict(X) - y) ** 2)))
    r_off = float(np.sqrt(np.mean((b_off.predict(X) - y) ** 2)))
    assert r_on <= r_off * 1.1, (r_on, r_off)
    # quality must be real: beat the label standard deviation comfortably
    assert r_on < float(np.std(y)) * 0.6


def test_bundled_predict_consistency_and_importance(onehot_data):
    X, y = onehot_data
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1,
              "min_data_in_leaf": 5}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=20)
    # predict on fresh rows goes through transform->merge: same code path
    pred_a = b.predict(X[:100])
    pred_b = b.predict(X[:100])
    np.testing.assert_array_equal(pred_a, pred_b)
    imp = b.feature_importance()
    assert imp.shape == (X.shape[1],)  # original feature space
    assert imp.sum() > 0
    # dense informative feature 0 must receive importance
    assert imp[0] > 0


def test_bundler_save_load_roundtrip(onehot_data, tmp_path):
    X, y = onehot_data
    params = {"objective": "regression", "num_leaves": 31, "verbosity": -1}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=10)
    path = str(tmp_path / "m.json")
    b.save_model(path)
    b2 = lgb.Booster(model_file=path)
    np.testing.assert_allclose(b.predict(X[:200]), b2.predict(X[:200]),
                               rtol=1e-6, atol=1e-7)


def test_conflict_rate_zero_keeps_conflicting_features_apart():
    rng = np.random.default_rng(3)
    n = 4000
    # two sparse features that are non-default TOGETHER on 5% of rows
    a = np.where(rng.random(n) < 0.1, rng.normal(2, 1, n), 0.0)
    both = rng.random(n) < 0.05
    b = np.where(both, rng.normal(-2, 1, n), 0.0)
    a = np.where(both, rng.normal(2, 1, n), a)
    dense = rng.normal(size=(n, 2))
    X = np.column_stack([dense, a, b]).astype(np.float32)
    codes = None
    ds = lgb.Dataset(X, label=rng.normal(size=n).astype(np.float32))
    ds.construct()
    bundler = ds.bin_mapper.bundler
    if bundler is not None:
        for g in bundler.groups:
            assert not ({2, 3} <= set(g)), \
                "conflicting features must not share a bundle at rate 0"

"""Param schema tests: the reference's param dicts must resolve verbatim."""

import warnings

import pytest

from lightgbm_tpu.config import Params, default_metric_for_objective, parse_params


def test_reference_grid_row_params():
    # a row of the r/gridsearchCV.R:92-100 grid, passed as params
    p = parse_params({
        "learning_rate": 0.05,
        "num_leaves": 63,
        "min_data_in_leaf": 40,
        "feature_fraction": 0.8,
        "bagging_fraction": 0.6,
        "bagging_freq": 4,
        "nthread": 4,          # rides through params, maps to ignored knob
        "objective": "regression",
    })
    assert p.learning_rate == 0.05
    assert p.num_leaves == 63
    assert p.min_data_in_leaf == 40
    assert p.feature_fraction == 0.8
    assert p.bagging_fraction == 0.6
    assert p.bagging_freq == 4
    assert p.num_threads == 4
    assert p.objective == "regression"


def test_aliases_resolve():
    p = parse_params({"eta": 0.02, "max_leaf_nodes": 31, "min_child_samples": 7,
                      "subsample": 0.9, "colsample_bytree": 0.5,
                      "reg_alpha": 0.1, "reg_lambda": 0.2,
                      "n_estimators": 77, "random_state": 11})
    assert p.learning_rate == 0.02
    assert p.num_leaves == 31
    assert p.min_data_in_leaf == 7
    assert p.bagging_fraction == 0.9
    assert p.feature_fraction == 0.5
    assert p.lambda_l1 == pytest.approx(0.1)
    assert p.lambda_l2 == pytest.approx(0.2)
    assert p.num_iterations == 77
    assert p.seed == 11


def test_unknown_param_warns_not_raises():
    with warnings.catch_warnings(record=True) as w:
        warnings.simplefilter("always")
        p = parse_params({"definitely_not_a_param": 3})
    assert any("definitely_not_a_param" in str(x.message) for x in w)
    assert p.extra["definitely_not_a_param"] == 3


def test_metric_aliases():
    p = parse_params({"metric": "rmse"})
    assert p.metric == ["rmse"]
    p = parse_params({"eval": "rmse"})  # the R binding arg name
    assert p.metric == ["rmse"]
    p = parse_params({"metric": ["l2", "mae"]})
    assert p.metric == ["l2", "l1"]


def test_objective_aliases():
    assert parse_params({"objective": "mse"}).objective == "regression"
    assert parse_params({"objective": "reg:linear"}).objective == "regression"
    assert parse_params({"objective": "binary:logistic"}).objective == "binary"


def test_default_metric_is_l2_for_regression():
    # the sweep relies on default-l2 when eval is omitted (SURVEY §2A row 2g)
    assert default_metric_for_objective("regression") == "l2"
    assert default_metric_for_objective("binary") == "binary_logloss"


def test_validation_errors():
    with pytest.raises(ValueError):
        parse_params({"num_leaves": 1})
    with pytest.raises(ValueError):
        parse_params({"bagging_fraction": 0.0})
    with pytest.raises(ValueError):
        parse_params({"objective": "not_an_objective"})


def test_rf_mode_forces_bagging():
    p = parse_params({"boosting": "rf"})
    assert p.bagging_freq >= 1
    assert 0 < p.bagging_fraction < 1

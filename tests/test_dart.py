"""DART boosting (dropout trees; upstream dart.hpp semantics)."""

import numpy as np
import pytest

import lightgbm_tpu as lgb


@pytest.fixture(scope="module")
def reg_data():
    rng = np.random.default_rng(12)
    n = 3000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    y = (X[:, 0] * 2 + np.sin(X[:, 1] * 3) + 0.5 * X[:, 2] * X[:, 3]
         + rng.normal(0, 0.1, n)).astype(np.float32)
    return X, y


def test_dart_trains_and_fits(reg_data):
    X, y = reg_data
    params = {"boosting": "dart", "objective": "regression",
              "num_leaves": 15, "learning_rate": 0.2, "verbosity": -1,
              "drop_rate": 0.3, "skip_drop": 0.3}
    b = lgb.train(params, lgb.Dataset(X, label=y), num_boost_round=40)
    assert b.num_trees() == 40
    rmse = float(np.sqrt(np.mean((b.predict(X) - y) ** 2)))
    # must clearly beat predicting the mean
    assert rmse < float(np.std(y)) * 0.4, rmse


def test_dart_quality_comparable_to_gbdt(reg_data):
    X, y = reg_data
    base = {"objective": "regression", "num_leaves": 15,
            "learning_rate": 0.2, "verbosity": -1}
    b_gbdt = lgb.train(dict(base), lgb.Dataset(X, label=y),
                       num_boost_round=40)
    b_dart = lgb.train(dict(base, boosting="dart", drop_rate=0.1),
                       lgb.Dataset(X, label=y), num_boost_round=40)
    r_g = float(np.sqrt(np.mean((b_gbdt.predict(X) - y) ** 2)))
    r_d = float(np.sqrt(np.mean((b_dart.predict(X) - y) ** 2)))
    assert r_d < r_g * 2.0, (r_d, r_g)


def test_dart_deterministic_under_seed(reg_data):
    X, y = reg_data
    params = {"boosting": "dart", "objective": "regression",
              "num_leaves": 15, "verbosity": -1, "drop_rate": 0.3,
              "seed": 7}
    a = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=15)
    b = lgb.train(dict(params), lgb.Dataset(X, label=y), num_boost_round=15)
    np.testing.assert_array_equal(a.predict(X[:200]), b.predict(X[:200]))


def test_dart_with_valid_set_early_stopping(reg_data):
    X, y = reg_data
    tr, va = np.arange(0, 2400), np.arange(2400, 3000)
    dtrain = lgb.Dataset(X[tr], label=y[tr])
    dvalid = dtrain.create_valid(X[va], label=y[va])
    params = {"boosting": "dart", "objective": "regression",
              "num_leaves": 15, "verbosity": -1, "drop_rate": 0.2}
    b = lgb.train(params, dtrain, num_boost_round=30, valid_sets=[dvalid],
                  early_stopping_rounds=10)
    # valid-set incremental predictions must track the DART rescaling:
    # compare incremental vpred against a fresh full predict
    name, vds, vpred = b._valid[0]
    fresh = b.predict(X[va], num_iteration=b.num_trees())
    np.testing.assert_allclose(
        np.asarray(vpred)[: len(va)], fresh, rtol=1e-4, atol=1e-5)


def test_dart_multiclass():
    """DART with multiclass: per-class trees dropped/rescaled together
    (the drop set is per ROUND, matching upstream's round-level dropout)."""
    import numpy as np
    import lightgbm_tpu as lgb

    rng = np.random.default_rng(31)
    n, K = 1500, 3
    X = rng.normal(size=(n, 4)).astype(np.float32)
    y = np.argmax(X[:, :K] + 0.5 * rng.normal(size=(n, K)),
                  axis=1).astype(np.float32)
    b = lgb.train({"objective": "multiclass", "num_class": K,
                   "boosting": "dart", "drop_rate": 0.3, "skip_drop": 0.0,
                   "num_leaves": 7, "verbosity": -1},
                  lgb.Dataset(X[:1200], label=y[:1200]),
                  num_boost_round=15)
    proba = b.predict(X[1200:])
    np.testing.assert_allclose(proba.sum(axis=1), 1.0, rtol=1e-5)
    acc = float(np.mean(np.argmax(proba, axis=1) == y[1200:]))
    assert acc > 0.65, acc
    # the maintained train predictions match a fresh predict (drop/rescale
    # bookkeeping is consistent)
    tp = np.asarray(b._pred_train)[:1200]
    pp = b.predict(X[:1200], raw_score=True)
    np.testing.assert_allclose(tp, pp, rtol=2e-3, atol=2e-3)

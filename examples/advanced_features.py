"""Advanced-feature tour: monotone constraints, linear leaves, TreeSHAP,
learning-rate schedules.

Demonstrates the LightGBM-parity surface beyond the reference snippets'
core workflow (r/gridsearchCV.R exercises train/cv/predict; this script
covers the constrained / interpretable / scheduled training modes a
LightGBM user would reach for next).

Run: python examples/advanced_features.py
"""

import sys

import numpy as np

sys.path.insert(0, ".")

import lightgbm_tpu as lgb


def main() -> None:
    rng = np.random.default_rng(7)
    n = 5000
    X = rng.normal(size=(n, 5)).astype(np.float32)
    # ground truth: increasing in x0, decreasing in x1, piecewise-linear
    # kink in x2, x3 noise-only
    y = (1.2 * X[:, 0] - 0.8 * X[:, 1]
         + np.where(X[:, 2] > 0, 2.0 * X[:, 2], 0.3 * X[:, 2])
         + 0.1 * rng.normal(size=n)).astype(np.float32)
    tr, te = slice(0, 4000), slice(4000, None)
    dtrain = lgb.Dataset(X[tr], label=y[tr])

    def rmse(b, **kw):
        return float(np.sqrt(np.mean((b.predict(X[te], **kw) - y[te]) ** 2)))

    # 1. monotone constraints: force the x0/x1 directions the truth has
    b_mono = lgb.train({"objective": "regression", "verbosity": -1,
                        "monotone_constraints": [1, -1, 0, 0, 0]},
                       dtrain, num_boost_round=60)
    print(f"monotone-constrained RMSE: {rmse(b_mono):.4f}")

    # 2. linear leaves: the x2 kink needs 2 linear leaves, not 20 steps
    b_lin = lgb.train({"objective": "regression", "verbosity": -1,
                       "num_leaves": 8, "linear_tree": True},
                      dtrain, num_boost_round=25)
    b_con = lgb.train({"objective": "regression", "verbosity": -1,
                       "num_leaves": 8}, dtrain, num_boost_round=25)
    print(f"linear leaves RMSE: {rmse(b_lin):.4f}  "
          f"(constant leaves: {rmse(b_con):.4f})")

    # 3. TreeSHAP: per-feature attribution; x3/x4 should get ~nothing
    contrib = b_mono.predict(X[te][:500], pred_contrib=True)
    mean_abs = np.abs(contrib[:, :5]).mean(axis=0)
    print("mean |SHAP| by feature:",
          np.array2string(mean_abs, precision=3))
    check = np.abs(contrib.sum(axis=1)
                   - b_mono.predict(X[te][:500], raw_score=True)).max()
    print(f"SHAP additivity check (max |sum phi - raw|): {check:.2e}")

    # 4. learning-rate decay via reset_parameter
    b_sched = lgb.train(
        {"objective": "regression", "verbosity": -1, "learning_rate": 0.3},
        dtrain, num_boost_round=60,
        callbacks=[lgb.reset_parameter(
            learning_rate=lambda i: 0.3 * (0.97 ** i))])
    print(f"lr-schedule RMSE: {rmse(b_sched):.4f}")


if __name__ == "__main__":
    main()

"""Python port of the reference GridSearchCV workflow.

Faithful re-run of /root/reference/r/gridsearchCV.R (and the `LightGBM
R.ipynb` notebook) against the TPU framework:

  data prep (log target)            r/gridsearchCV.R:5-18
  85/15 Bernoulli split, seeded     r/gridsearchCV.R:20-34
  linear baseline (glmnet lambda=0) r/gridsearchCV.R:45-46  -> LinearRegression
  untuned GBDT, 200 rounds, timed   r/gridsearchCV.R:52-64
  5-fold CV, early stopping         r/gridsearchCV.R:70-81
  108-config expand.grid            r/gridsearchCV.R:92-102
  checkpointed sweep loop           r/gridsearchCV.R:104-119
  top-m ensemble of predictions     r/gridsearchCV.R:122-144

The real ggplot2 `diamonds` data is not fetchable offline, so a structurally
matched synthetic stands in (lightgbm_tpu.utils.datasets); expected values are
therefore quality-ladder bands, not the reference's exact RMSEs (SURVEY.md §4).

Run:  python examples/gridsearch_cv.py [--quick]
"""

import argparse
import sys
import time

import numpy as np

sys.path.insert(0, ".")

import lightgbm_tpu as lgb
from lightgbm_tpu.utils.datasets import (
    make_synthetic_diamonds,
    train_test_split_bernoulli,
)
from lightgbm_tpu.utils.sweep import expand_grid, run_grid_search


def rmse(y, pred):
    # r/gridsearchCV.R:29 `rmse <- function(y, pred) sqrt(mean((y-pred)^2))`
    return float(np.sqrt(np.mean((y - pred) ** 2)))


def main(quick: bool = False) -> None:
    # -- data prep + split (r/gridsearchCV.R:5-34) -------------------------
    X, y, names = make_synthetic_diamonds()
    tr, te = train_test_split_bernoulli(len(y), p_train=0.85, seed=3928272)
    X_train, y_train, X_test, y_test = X[tr], y[tr], X[te], y[te]
    print(f"train {len(tr)} rows, test {len(te)} rows, {len(names)} features")

    # -- linear baseline (r/gridsearchCV.R:45-46, glmnet lambda=0) ---------
    from sklearn.linear_model import LinearRegression

    lin = LinearRegression().fit(X_train, y_train)
    rmse_lin = rmse(y_test, lin.predict(X_test))
    print(f"linear model test RMSE: {rmse_lin:.7f}   (reference: 0.1455686)")

    # -- untuned GBDT, 200 rounds, timed (r/gridsearchCV.R:52-64) ----------
    dtrain = lgb.Dataset(X_train, label=y_train)
    dtrain.construct()
    params = {"learning_rate": 0.1, "objective": "regression", "verbosity": 0}
    t0 = time.perf_counter()
    fit = lgb.train(params, dtrain, num_boost_round=200)
    elapsed = time.perf_counter() - t0
    rmse_gbdt = rmse(y_test, fit.predict(X_test))
    print(f"untuned GBDT: {elapsed:.2f}s for 200 rounds "
          f"(reference: ~1.02s on 2017 CPU)")
    print(f"untuned GBDT test RMSE: {rmse_gbdt:.7f}  (reference: 0.09566155)")
    assert rmse_gbdt < rmse_lin, "GBDT must beat the linear baseline"

    # -- 5-fold CV with early stopping (r/gridsearchCV.R:70-81) ------------
    cvfit = lgb.cv(params, dtrain, num_boost_round=1000, nfold=5,
                   metrics="rmse", early_stopping_rounds=5, stratified=False,
                   seed=3928272)
    print(f"cv best_iter: {cvfit.best_iter}  (reference run: 300)")
    print(f"cv best_score: {cvfit.best_score:.7f}  "
          f"(reference: -0.09676132, sign-flipped RMSE)")

    # -- the 108-config grid (r/gridsearchCV.R:92-102) ---------------------
    grid = expand_grid(
        learning_rate=[0.1, 0.05, 0.01],
        num_leaves=[31, 63, 127],
        min_data_in_leaf=[20, 40],
        feature_fraction=[0.8, 1.0],
        bagging_fraction=[0.6, 0.8, 1.0],
        bagging_freq=[4],
        nthread=[4],
    )
    print(f"grid size: {len(grid)}  (reference: 108)")
    if quick:
        grid = grid[:4]
        print(f"--quick: truncated to {len(grid)} configs")

    # -- checkpointed sweep (r/gridsearchCV.R:104-119) ---------------------
    # hist_dtype=bf16: bf16 MXU histogram inputs with f32 accumulation —
    # ~2.3x faster sweeps, cv scores within fold-noise of full f32
    # (validated: best l2 agrees to 3 decimals on this workload)
    t0 = time.perf_counter()
    ledger = run_grid_search(
        grid, dtrain,
        base_params={"objective": "regression", "verbosity": 0,
                     "hist_dtype": "bf16"},
        num_boost_round=1000, nfold=5, early_stopping_rounds=5,
        ledger_path="paramGrid.json", seed=3928272)
    sweep_s = time.perf_counter() - t0
    print(f"sweep wall time: {sweep_s / 60:.1f} min "
          f"(reference: ~30 min serial CPU)")

    # -- leaderboard + top-m ensemble (r/gridsearchCV.R:122-144) -----------
    board = ledger.leaderboard()
    print("top-3 configs:")
    for r in board[:3]:
        print("  ", {k: v for k, v in r.items() if k != "nthread"})

    m = 5  # r/gridsearchCV.R:125 uses m=5 (the notebook uses 3)
    preds = []
    for r in board[:m]:
        p = {k: v for k, v in r.items()
             if k not in ("iteration", "score", "nthread")}
        p.update({"objective": "regression", "verbosity": 0})
        boost = lgb.train(p, dtrain, num_boost_round=int(r["iteration"]))
        preds.append(boost.predict(X_test))  # keep predictions, no model
    ens = np.mean(np.column_stack(preds), axis=1)  # rowMeans equivalent
    rmse_ens = rmse(y_test, ens)
    print(f"top-{m} ensemble test RMSE: {rmse_ens:.7f} "
          f"(reference: 0.09437292)")
    print("quality ladder:",
          f"linear {rmse_lin:.4f} > untuned {rmse_gbdt:.4f} >= "
          f"ensemble {rmse_ens:.4f}")


if __name__ == "__main__":
    ap = argparse.ArgumentParser()
    ap.add_argument("--quick", action="store_true",
                    help="run only 4 grid configs (smoke test)")
    main(**vars(ap.parse_args()))

"""Python port of /root/reference/bagging_boosting.ipynb.

Demonstrates boosting (staged predictions over round prefixes) versus bagging
(random-forest averaging) on the notebook's synthetic 1-D curve
``y = |x| + cos(x)`` (bagging_boosting.ipynb:67-74), with the xgboost calls
re-dispatched to the TPU framework:

  xgb.DMatrix           -> lgb.Dataset                      (:118-119)
  xgb.cv                -> lgb.cv                           (:128)
  xgb.train             -> lgb.train                        (:131)
  predict(ntree_limit=) -> booster.predict(ntree_limit=)    (:134-136)
  RandomForestRegressor -> LGBMRandomForestRegressor        (:204-206)

Run:  python examples/bagging_boosting.py
"""

import sys
import time

import numpy as np

sys.path.insert(0, ".")

import lightgbm_tpu as lgb
from lightgbm_tpu.sklearn import LGBMRandomForestRegressor
from lightgbm_tpu.utils.datasets import make_boosting_curve


def main() -> None:
    # notebook cell 2: data (np.random.seed(8657), n=1000, noise U(-.05,.05))
    X, y = make_boosting_curve(n=1000, seed=8657)
    grid = np.linspace(-4, 4, 400).reshape(-1, 1)
    truth = np.abs(grid[:, 0]) + np.cos(grid[:, 0])

    # notebook cell 4: boosting params {eta:0.02, max_depth:6,
    # max_leaf_nodes:31} — eta/max_leaf_nodes resolve via the alias table.
    params = {"objective": "reg:linear", "eval_metric": "rmse", "eta": 0.02,
              "max_depth": 6, "max_leaf_nodes": 31, "verbosity": 0,
              "min_data_in_leaf": 1}
    dtrain = lgb.Dataset(X, label=y)
    dtrain.construct()

    t0 = time.perf_counter()
    cvres = lgb.cv(params, dtrain, num_boost_round=1000,
                   early_stopping_rounds=50, nfold=5, stratified=False)
    print(f"cv: {time.perf_counter() - t0:.2f}s "
          f"(reference xgb.cv: 5.01s), best_iter={cvres.best_iter}, "
          f"rmse={-cvres.best_score if cvres.best_score < 0 else cvres.best_score:.4f}")

    t0 = time.perf_counter()
    model = lgb.train(params, dtrain, num_boost_round=500)
    print(f"train: {time.perf_counter() - t0:.2f}s "
          f"(reference xgb.train: 1.42s)")

    # notebook cell 7: staged predictions at tree prefixes {1,20,50,100,300}
    # — the notebook's deliverable is the matplotlib figure of these staged
    # fits over the scatter (bagging_boosting.ipynb:134-136)
    print("boosting: staged fit RMSE vs true curve by rounds used")
    staged = {}
    for k in (1, 20, 50, 100, 300):
        pred = model.predict(grid, ntree_limit=k)
        staged[k] = pred
        err = float(np.sqrt(np.mean((pred - truth) ** 2)))
        print(f"  first {k:>3} trees: RMSE vs truth {err:.4f}")

    # notebook cell 8-9: bagging with 1 / 3 / 100 trees
    # (RandomForestRegressor(n_estimators, max_leaf_nodes=20, max_features=1,
    #  random_state=345)); figures at bagging_boosting.ipynb:195-213
    print("bagging: random-forest fit RMSE vs true curve by forest size")
    bagged = {}
    for n_trees in (1, 3, 100):
        rf = LGBMRandomForestRegressor(
            n_estimators=n_trees, max_leaf_nodes=20, max_features=1,
            random_state=345, min_samples_leaf=3)
        rf.fit(X, y)
        pred = rf.predict(grid)
        bagged[n_trees] = pred
        err = float(np.sqrt(np.mean((pred - truth) ** 2)))
        print(f"  {n_trees:>3} trees: RMSE vs truth {err:.4f}")

    print("expected shape: boosting error falls with more rounds; "
          "bagging error falls with more trees (variance reduction)")
    _save_plots(X, y, grid, truth, staged, bagged)


def _save_plots(X, y, grid, truth, staged, bagged) -> None:
    """The notebook's actual output: staged-boosting and forest-size figures
    (bagging_boosting.ipynb:134-136, 195-213), saved as PNGs (headless)."""
    import matplotlib

    matplotlib.use("Agg")
    import matplotlib.pyplot as plt

    fig, axes = plt.subplots(1, 2, figsize=(12, 4.5), sharey=True)
    for ax, (title, curves) in zip(axes, [
            ("Boosting: fit after k rounds", staged),
            ("Bagging: forest of n trees", bagged)]):
        ax.scatter(X[:, 0], y, s=4, c="lightgray", label="data")
        ax.plot(grid[:, 0], truth, "k--", lw=1, label="truth")
        for k, pred in curves.items():
            ax.plot(grid[:, 0], pred, lw=1.2, label=f"{k}")
        ax.set_title(title)
        ax.set_xlabel("x")
        ax.legend(fontsize=8)
    axes[0].set_ylabel("y")
    fig.tight_layout()
    out = "bagging_boosting.png"
    fig.savefig(out, dpi=110)
    plt.close(fig)
    print(f"wrote {out}")


if __name__ == "__main__":
    main()
